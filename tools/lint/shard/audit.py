"""The shard-stage auditor: mesh-aware lowering analysis + contract checking.

For every registered :class:`~.types.ShardEntry` this module

* lowers the program (the entry's thunk — ``fn.lower(...)`` under the
  entry's mesh; abstract avals, no device execution) and reads the
  ``@main`` signature: per-argument/per-result ``mhlo.sharding``
  attributes (what GSPMD is actually handed), explicit ``stablehlo.*``
  collective ops, and ``custom_call @Sharding`` constraint sites net of
  shard_map boundary markers (``@SPMDFullToShardShape`` /
  ``@SPMDShardToFullShape``);
* for ``partitioned`` entries (multi-device meshes) ALSO compiles the
  lowered program on the host-platform device mesh and counts the
  collectives in the post-SPMD-partitioning HLO — the ground truth that
  includes every all-gather/all-reduce GSPMD *inserted*, which is
  exactly what the lowered text cannot show.

The per-entry facts are checked against the committed contract file
(``tools/shard_contracts.json``), yielding DTL15x findings (code table
in ``tools/lint/shard/__init__.py``). ``emit_contract`` regenerates the
contract from the current registry — the blessed-update workflow, the
same shape as the trace stage's.

Collective counts come from COMPILED programs, so they depend on the
XLA pass pipeline; the audit pins ``jax_disable_most_optimizations``
(True — the rawest, most deterministic partitioner output, and the
test suite's own setting) for the duration of every audit and restores
it after, so the committed counts are identical in-process under
pytest, under the CLI, and inside the multichip dryrun's provenance
cross-check.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import Finding
from ..trace.audit import _def_line, _load_registry
from .types import ShardEntry

# canonical op-kind names (contract keys); left = compiled-HLO spelling,
# right = lowered-StableHLO spelling
_COLLECTIVE_OPS: Tuple[Tuple[str, str], ...] = (
    ("all-gather", "all_gather"),
    ("all-reduce", "all_reduce"),
    ("reduce-scatter", "reduce_scatter"),
    ("collective-permute", "collective_permute"),
    ("all-to-all", "all_to_all"),
)

_ARG_RE = re.compile(r"%arg(\d+): (tensor<[^>]*>)")
_SHARD_RE = re.compile(r'mhlo\.sharding = "([^"]*)"')


@contextlib.contextmanager
def _pinned_compile_flags():
    """Pin the XLA pipeline knob the collective counts depend on, restore
    on exit (the audit may run in-process inside pytest or a bench)."""
    import jax

    prev = bool(jax.config._read("jax_disable_most_optimizations"))
    jax.config.update("jax_disable_most_optimizations", True)
    try:
        yield
    finally:
        jax.config.update("jax_disable_most_optimizations", prev)


# --------------------------------------------------------------- parsing


def _main_region(text: str) -> Tuple[str, str]:
    """(argument region, result region) of the lowered module's ``@main``
    signature. Bracket matching is quote-aware: HLO sharding strings
    contain unbalanced ``<=`` tokens that would wreck naive depth
    counting."""
    start = text.find("@main(")
    if start < 0:
        return "", ""
    i = start + len("@main(")
    args, j = _balanced(text, i)
    arrow = text.find("->", j)
    if arrow < 0:
        return args, ""
    k = text.find("(", arrow)
    newline = text.find("\n", arrow)
    if k < 0 or (newline >= 0 and k > newline):
        # single unparenthesized result type
        end = newline if newline >= 0 else len(text)
        region = text[arrow + 2:end].strip().rstrip("{").strip()
        return args, region
    res, _ = _balanced(text, k + 1)
    return args, res


def _balanced(text: str, i: int) -> Tuple[str, int]:
    """Text up to the paren that closes the one just before ``i``,
    skipping quoted strings."""
    depth, j, in_str = 1, i, False
    while j < len(text) and depth:
        c = text[j]
        if in_str:
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        j += 1
    return text[i:j - 1], j


def _split_top(region: str) -> List[str]:
    """Split a type-list region on top-level commas (quote- and
    bracket-aware; ``tensor<...>`` angle brackets carry no commas, and
    sharding strings are inside quotes)."""
    out, buf, depth, in_str = [], "", 0, False
    for c in region:
        if in_str:
            buf += c
            if c == '"':
                in_str = False
            continue
        if c == '"':
            in_str = True
            buf += c
            continue
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
        if c == "," and depth == 0:
            out.append(buf)
            buf = ""
        else:
            buf += c
    if buf.strip():
        out.append(buf)
    return out


def parse_main_shardings(
    text: str,
) -> Tuple[List[Optional[str]], List[Optional[str]]]:
    """Per-argument and per-result ``mhlo.sharding`` strings (None when
    the attribute is absent) from the lowered ``@main`` signature."""
    arg_region, res_region = _main_region(text)
    matches = list(_ARG_RE.finditer(arg_region))
    args: List[Optional[str]] = []
    for k, m in enumerate(matches):
        seg_end = (matches[k + 1].start() if k + 1 < len(matches)
                   else len(arg_region))
        seg = arg_region[m.start():seg_end]
        sh = _SHARD_RE.search(seg)
        args.append(sh.group(1) if sh else None)
    outs: List[Optional[str]] = []
    for seg in _split_top(res_region):
        sh = _SHARD_RE.search(seg)
        outs.append(sh.group(1) if sh else None)
    return args, outs


def lowered_collectives(text: str) -> Dict[str, int]:
    """Explicit collective ops in PRE-partitioning StableHLO — shard_map
    psums/ppermutes the source wrote. GSPMD-inserted collectives do not
    exist yet at this level (see :func:`compiled_collectives`)."""
    out: Dict[str, int] = {}
    for canon, st in _COLLECTIVE_OPS:
        n = len(re.findall(rf"stablehlo\.{st}\b", text))
        if n:
            out[canon] = n
    return out


def compiled_collectives(text: str) -> Dict[str, int]:
    """Collective instructions in POST-partitioning compiled HLO (async
    ``-start`` forms count once; ``-done`` halves don't)."""
    out: Dict[str, int] = {}
    for canon, _ in _COLLECTIVE_OPS:
        # opcode-followed-by-operands; operand REFERENCES (`%all-reduce.3`)
        # never carry the paren, and tuple-shaped results (`= (f32[..],
        # f32[..]) all-to-all(`) rule out anchoring on the result type
        n = len(re.findall(rf"\b{canon}(?:-start)?\(", text))
        if n:
            out[canon] = n
    return out


_SHARDING_SITE_RE = re.compile(
    r"(%[\w.#]+)\s*=\s*stablehlo\.custom_call @Sharding\("
    r'[^)]*\)\s*\{backend_config = "([^"]*)"'
)
_SPMD_MARKER_RE = re.compile(
    r"@SPMD(?:FullToShardShape|ShardToFullShape)\((%[\w.#]+)"
)


def reshard_constraints(text: str) -> int:
    """In-program ``@Sharding`` constraint sites NOT attributable to a
    shard_map boundary. A boundary ``@Sharding``'s SSA result is consumed
    directly by a ``@SPMDFullToShardShape``/``@SPMDShardToFullShape``
    marker (jax's shard_map lowering emits the pair on every operand and
    result, in full-manual and partial-manual mode alike) — those are
    declared spec boundaries. Markers with a non-empty
    ``unspecified_dims`` backend config are jax's internal partial-
    sharding annotations (key arrays, partial-manual operands), not
    programmer constraints, and are excluded too. What remains is the
    ``with_sharding_constraint``-shaped reshard point a program declares
    mid-flight — each one a potential device-to-device copy, so the
    count is contract-budgeted (DTL154)."""
    boundary_values = set(_SPMD_MARKER_RE.findall(text))
    n = 0
    for value, backend_config in _SHARDING_SITE_RE.findall(text):
        if backend_config:
            continue
        if value in boundary_values:
            continue
        n += 1
    return n


def _digest(items: Sequence[Optional[str]]) -> str:
    joined = "\n".join("-" if x is None else x for x in items)
    return hashlib.sha1(joined.encode()).hexdigest()[:16]


def _spec_repr(spec) -> str:
    return repr(tuple(spec))


# --------------------------------------------------------------- auditing


def audit_shard_entry(ep: ShardEntry) -> Dict[str, Any]:
    """Lower (and for multi-device meshes compile) one entry; return the
    per-entry report the checkers and ``--emit-contract`` consume."""
    with _pinned_compile_flags():
        lowered = ep.lower()
        text = lowered.as_text()
        explicit = lowered_collectives(text)
        if ep.partitioned:
            level = "partitioned"
            collectives = compiled_collectives(
                lowered.compile().as_text()
            )
        else:
            level = "lowered"
            collectives = dict(explicit)

    actual_in, actual_out = parse_main_shardings(text)
    # jit drops unused args from the lowered module (keep_unused=False is
    # the production default — the canonical loss ignores its rng, so
    # that key never reaches @main); align the EXPECTED per-arg list
    # through the lowering's kept-variable indices
    arg_paths = list(ep.arg_paths)
    in_expected = list(ep.in_shardings)
    pos_of = {i: i for i in range(len(actual_in))}
    if in_expected and len(in_expected) != len(actual_in):
        try:
            kept = sorted(lowered._lowering.compile_args["kept_var_idx"])
        except (AttributeError, KeyError, TypeError):
            kept = None
        if kept is not None and len(kept) == len(actual_in) \
                and (not kept or kept[-1] < len(in_expected)):
            arg_paths = [ep.arg_paths[i] for i in kept]
            in_expected = [ep.in_shardings[i] for i in kept]
            pos_of = {orig: p for p, orig in enumerate(kept)}
    # the intent->arg join is only sound when expected and lowered args
    # line up 1:1; when they don't (kept_var_idx unavailable on a future
    # jax), the <arity> DTL152 mismatch below fails the gate LOUDLY and
    # DTL153 must stay silent rather than misjoin to the wrong args
    intents_judgeable = (not ep.in_shardings
                         or len(in_expected) == len(actual_in))

    in_mismatches: List[Tuple[str, str, str]] = []
    out_mismatches: List[Tuple[str, str, str]] = []
    if in_expected:
        if len(in_expected) != len(actual_in):
            in_mismatches.append((
                "<arity>", f"{len(in_expected)} args",
                f"{len(actual_in)} args",
            ))
        for path, exp, act in zip(arg_paths, in_expected, actual_in):
            if exp is not None and act != exp:
                in_mismatches.append((path, exp, act or "<none>"))
    if ep.out_shardings:
        if len(ep.out_shardings) != len(actual_out):
            out_mismatches.append((
                "<arity>", f"{len(ep.out_shardings)} results",
                f"{len(actual_out)} results",
            ))
        for path, exp, act in zip(ep.out_paths, ep.out_shardings, actual_out):
            if exp is not None and act != exp:
                out_mismatches.append((path, exp, act or "<none>"))

    # DTL153: rule-engine intent said "sharded", the lowered program says
    # "fully replicated" — join on the flattened argument index. An arg
    # jit DROPPED (absent from pos_of) never reaches @main at all: that
    # is unused, not replicated — skip it rather than misreport.
    replicated_intents: List[Dict[str, Any]] = []
    for intent in ep.param_intents:
        if not intents_judgeable or not intent.get("intent_sharded"):
            continue
        pos = pos_of.get(intent.get("arg"))
        if pos is None or pos >= len(actual_in):
            continue
        act = actual_in[pos]
        if act is None or "replicated" in act or "maximal" in act:
            replicated_intents.append(intent)

    param_specs = {
        intent["path"]: _spec_repr(intent["spec"])
        for intent in ep.param_intents
        if intent.get("intent_sharded")
    }

    return {
        "name": ep.name,
        "path": ep.path,
        "symbol": ep.symbol,
        "mesh": dict(ep.mesh_axes),
        "level": level,
        "collectives": collectives,
        "explicit_collectives": explicit,
        "reshard_constraints": reshard_constraints(text),
        "in_args": len(actual_in),
        "out_vals": len(actual_out),
        "sharded_in_args": sum(
            1 for s in actual_in
            if s is not None and "replicated" not in s and "maximal" not in s
        ),
        "in_sharding_digest": _digest(actual_in),
        "out_sharding_digest": _digest(actual_out),
        "in_mismatches": in_mismatches,
        "out_mismatches": out_mismatches,
        "replicated_intents": replicated_intents,
        "param_specs": param_specs,
    }


# ---------------------------------------------------------- the contract


def load_contract(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(
            f"shard contract {path}: want a JSON object with an "
            f'"entries" map, got {type(data).__name__}'
        )
    return data


def emit_contract(reports: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Contract JSON derived from the current registry + audit — commit
    the output after an INTENTIONAL change (a renegotiated collective
    budget, a new sharding rule), exactly like re-baselining. What it
    CANNOT clear: DTL152 lowered-vs-derived drift and DTL153 accidental
    replication live in the code, not the contract."""
    entries: Dict[str, Any] = {}
    for r in sorted(reports, key=lambda r: r["name"]):
        entries[r["name"]] = {
            "path": r["path"],
            "mesh": r["mesh"],
            "level": r["level"],
            "collectives": {
                k: r["collectives"][k] for k in sorted(r["collectives"])
            },
            "max_reshard_constraints": r["reshard_constraints"],
            "in_sharding_digest": r["in_sharding_digest"],
            "out_sharding_digest": r["out_sharding_digest"],
            "sharded_in_args": r["sharded_in_args"],
            "param_specs": {
                k: r["param_specs"][k] for k in sorted(r["param_specs"])
            },
        }
    return {"version": 1, "entries": entries}


def check_reports(
    reports: List[Dict[str, Any]],
    contract: Dict[str, Any],
    contract_path: str,
    repo_root: str,
) -> List[Finding]:
    """Compare audit reports against the committed contract; every
    divergence is a DTL15x finding anchored on the entry point."""
    findings: List[Finding] = []
    entries = contract.get("entries", {})
    by_name = {r["name"]: r for r in reports}

    def add(code, rep, msg, anchor_suffix=""):
        findings.append(Finding(
            code=code,
            path=rep["path"],
            line=_def_line(repo_root, rep["path"], rep["symbol"]),
            message=msg,
            anchor=rep["name"] + anchor_suffix,
        ))

    # ---- DTL155: registry <-> contract 1:1 (the DTL101/102 mirror) ----
    for name in sorted(set(entries) - set(by_name)):
        findings.append(Finding(
            code="DTL155", path=contract_path, line=1,
            message=f"contract entry '{name}' matches no registered shard "
                    f"entry point — prune it (the contract, like the "
                    f"baseline, can only track live code)",
            anchor=name,
        ))

    for rep in reports:
        name = rep["name"]
        c = entries.get(name)
        if c is None:
            add("DTL155", rep,
                f"shard entry point '{name}' has no committed contract "
                f"entry — run `python tools/lint.py --shard "
                f"--emit-contract` and review the diff")
            continue

        # ---- DTL151: per-op-kind collective budget --------------------
        budget = c.get("collectives", {})
        for op in sorted(rep["collectives"]):
            n = rep["collectives"][op]
            if op not in budget:
                add("DTL151", rep,
                    f"'{name}' ({rep['level']}) contains {n} {op} "
                    f"collective(s) the contract does not list — an "
                    f"unlisted collective is the silent-resharding bug "
                    f"class: HBM and ICI pay for it on every step",
                    anchor_suffix=f":{op}")
            elif n > budget[op]:
                add("DTL151", rep,
                    f"'{name}' ({rep['level']}) contains {n} {op} "
                    f"collective(s), contract budget is {budget[op]} — "
                    f"the program grew communication; if intentional, "
                    f"re-emit the contract", anchor_suffix=f":{op}")

        # ---- DTL152: in/out sharding-spec contract --------------------
        mismatches = rep["in_mismatches"] + rep["out_mismatches"]
        if mismatches:
            head = "; ".join(
                f"{p}: rules derive {e}, lowered program carries {a}"
                for p, e, a in mismatches[:3]
            )
            more = len(mismatches) - 3
            add("DTL152", rep,
                f"'{name}' lowered arg/result shardings drift from the "
                f"specs parallel/sharding.py derives ({len(mismatches)} "
                f"mismatch(es): {head}"
                + (f"; +{more} more" if more > 0 else "") + ") — the "
                f"rule engine and what GSPMD is handed no longer agree",
                anchor_suffix=":lowered")
        drift = []
        if rep["in_sharding_digest"] != c.get("in_sharding_digest"):
            drift.append("in-sharding digest")
        if rep["out_sharding_digest"] != c.get("out_sharding_digest"):
            drift.append("out-sharding digest")
        if rep["sharded_in_args"] != c.get("sharded_in_args"):
            drift.append(
                f"sharded-arg count {rep['sharded_in_args']} != "
                f"{c.get('sharded_in_args')}"
            )
        committed_specs = c.get("param_specs", {})
        if rep["param_specs"] != committed_specs:
            changed = sorted(
                set(rep["param_specs"].items())
                ^ set(committed_specs.items())
            )
            drift.append(
                "param specs "
                + ", ".join(f"{k}={v}" for k, v in changed[:3])
                + (f" +{len(changed) - 3} more" if len(changed) > 3 else "")
            )
        if drift:
            add("DTL152", rep,
                f"'{name}' sharding contract drift vs {contract_path}: "
                + "; ".join(drift) + " — if the rule change is "
                f"intentional, re-emit the contract",
                anchor_suffix=":contract")

        # ---- DTL153: accidental replication ---------------------------
        for intent in rep["replicated_intents"]:
            add("DTL153", rep,
                f"'{name}' parameter {intent['path']} is declared sharded "
                f"by rule {intent.get('rule')!r} "
                f"(requested {_spec_repr(intent['requested'])}) but the "
                f"lowered program replicates it — the fsdp/tp memory "
                f"story is fiction for this parameter",
                anchor_suffix=f":{intent['path']}")

        # ---- DTL154: in-program reshard constraints -------------------
        max_cons = c.get("max_reshard_constraints", 0)
        if rep["reshard_constraints"] > max_cons:
            add("DTL154", rep,
                f"'{name}' contains {rep['reshard_constraints']} "
                f"in-program sharding-constraint site(s) (net of "
                f"shard_map boundaries), budget {max_cons} — each "
                f"unbudgeted constraint is a potential device-to-device "
                f"reshard copy inside the hot program")

    return findings


# ------------------------------------------------------------ the runner


def run_shard(
    repo_root: str,
    registry_path: str,
    contract_path: str,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """The ``--shard`` stage: load the registry, audit every entry, check
    against the contract. Returns (findings, reports); findings feed the
    shared suppression/baseline machinery in ``core.run_lint``."""
    # contract problems are knowable in microseconds — check BEFORE the
    # multi-second lower/compile sweep
    ab_contract = (contract_path if os.path.isabs(contract_path)
                   else os.path.join(repo_root, contract_path))
    if not os.path.exists(ab_contract):
        raise OSError(
            f"shard contract file {contract_path} not found — generate "
            f"it with `python tools/lint.py --shard --emit-contract > "
            f"{contract_path}`"
        )
    contract = load_contract(ab_contract)
    mod = _load_registry(repo_root, registry_path)
    eps: List[ShardEntry] = mod.build_entry_points()
    reports = [audit_shard_entry(ep) for ep in eps]
    rel_contract = contract_path.replace(os.sep, "/")
    findings = check_reports(reports, contract, rel_contract, repo_root)
    return findings, reports


def shard_reports_only(repo_root: str, registry_path: str):
    """Audit without a contract (``--emit-contract`` path)."""
    mod = _load_registry(repo_root, registry_path)
    return [audit_shard_entry(ep) for ep in mod.build_entry_points()]
