"""The repo's shard-audit entry points: six mesh kinds + the serving jits.

This module — like the trace registry it is modeled on — IMPORTS the
package, because its job is to build the REAL programs production runs:

* ``make_train_step`` (donated state, NaN guard on, the canonical
  weighted-CE loss) lowered under the six mesh kinds from
  ``parallel/mesh.py`` (``dp``/``fsdp``/``tp``/``sp``/``pp``/``ep``)
  as seven entries — ``sp`` lowers twice, ring path and dual-balanced
  block-sparse path — each a 2-extent axis over the first two
  host-platform devices —
  abstract lowering plus one host-CPU compile per mesh, no TPU
  anywhere. The model is the trace stage's canonical config, varied only
  where an axis demands structure (``sp`` needs a ring-splittable
  sequence and an axial pattern, ``pp`` a pipeline axis, ``ep`` Switch-
  MoE feed-forwards) — the same variations the 8-device MULTICHIP
  dryrun proves bit-exact;
* every ``serving.*`` jit the TRACE registry declares, lowered as-is
  under its current 1-device placement. Their contract entries commit
  the "no collectives in serving" baseline that ROADMAP item 1
  (pjit-sharded replicas) will consciously renegotiate: the day a psum
  lands in a serving jit, DTL151 fires until the budget is re-emitted
  and reviewed.

Expected shardings come from ``parallel/sharding.py`` itself
(``params_shardings`` / ``opt_state_shardings`` / ``spec_report``) so
the committed contract tracks the rule engine, not a transcription of
it. Axis extents are 2 on purpose: collective COUNTS are structural
(they scale with program shape, not axis extent), and 2-device meshes
keep the audit fast-tier safe.
"""

from __future__ import annotations

from typing import Dict, List

from lint.trace.registry import CANON_MODEL
from lint.shard.types import ShardEntry

_STEP_PATH = "dalle_pytorch_tpu/parallel/step.py"

# per-mesh-kind model variation: an axis only exercises its collectives
# when the model has the structure the axis shards (mirrors the
# __graft_entry__.py dryrun configs). Rows are (entry_name, axis,
# model_kw, moe) — entry_name diverges from the axis when one axis is
# audited under more than one model structure: ``sp`` lowers twice,
# once on the ring path (full+axial_row) and once on the dual-balanced
# block-sparse path (axial_row+sparse), because the two paths have
# different collective contracts (permutes vs all-gathers).
MESH_KINDS = (
    ("dp", "dp", {}, False),
    ("fsdp", "fsdp", {}, False),
    ("tp", "tp", {}, False),
    ("sp", "sp", dict(attn_types=("full", "axial_row"), sp_axis="sp",
                      text_seq_len=8, image_fmap_size=4), False),
    ("sp_sparse", "sp", dict(attn_types=("axial_row", "sparse"),
                             sp_axis="sp", text_seq_len=8,
                             image_fmap_size=4), False),
    ("pp", "pp", dict(pp_axis="pp"), False),
    ("ep", "ep", dict(ff_experts=4, moe_every=1), True),
)


def _flat_paths_and_specs(tree, shardings):
    """Flattened (keystr path, expected HLO sharding string) pairs for an
    abstract arg/out pytree and its matching sharding pytree."""
    import jax

    path_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    sh_leaves = jax.tree_util.tree_flatten(shardings)[0]
    paths = [jax.tree_util.keystr(kp) for kp, _ in path_leaves]
    expected = [
        str(s._to_xla_hlo_sharding(len(leaf.shape)))
        for (kp, leaf), s in zip(path_leaves, sh_leaves)
    ]
    return paths, expected


def _train_shard_entry(
    name: str, kind: str, model_kw: Dict, moe: bool
) -> ShardEntry:
    """One mesh kind: the full sharded train step, lowered lazily."""
    import jax
    import jax.numpy as jnp
    import optax

    from dalle_pytorch_tpu.models import DALLE
    from dalle_pytorch_tpu.parallel.mesh import make_runtime
    from dalle_pytorch_tpu.parallel.sharding import (
        opt_state_shardings,
        params_shardings,
        params_spec_reports,
    )
    from dalle_pytorch_tpu.parallel.step import TrainState, make_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    SDS = jax.ShapeDtypeStruct
    cfg = dict(CANON_MODEL)
    cfg.update(model_kw)
    dalle = DALLE(**cfg)
    devices = jax.devices()
    if len(devices) < 2:
        raise ValueError(
            "the shard audit needs >= 2 host devices — run through "
            "tools/lint.py --shard (it forces an 8-device host platform) "
            "or set XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    runtime = make_runtime(devices=devices[:2], **{kind: 2})
    optimizer = optax.adam(1e-3)

    if moe:
        def loss_fn(params, batch, rng):
            out, mut = dalle.apply(
                {"params": params}, batch[0], batch[1],
                return_loss=True, mutable=["moe_aux"],
            )
            aux = sum(jax.tree_util.tree_leaves(mut.get("moe_aux", {})),
                      jnp.zeros((), jnp.float32))
            return out + 1e-2 * aux
    else:
        def loss_fn(params, batch, rng):
            return dalle.apply(
                {"params": params}, batch[0], batch[1], return_loss=True
            )

    batch = 2  # divisible by every 2-extent data axis
    text = SDS((batch, dalle.text_seq_len), jnp.int32)
    image = SDS((batch, dalle.image_seq_len), jnp.int32)
    params = jax.eval_shape(
        lambda t, i: dalle.init(jax.random.key(0), t, i), text, image
    )["params"]
    opt_state = jax.eval_shape(optimizer.init, params)
    i32 = SDS((), jnp.int32)
    state = TrainState(
        step=i32, params=params, opt_state=opt_state,
        skipped=i32, consec_skipped=i32,
    )
    p_shard = params_shardings(params, runtime.mesh)
    replicated = NamedSharding(runtime.mesh, P())
    shardings = TrainState(
        step=replicated, params=p_shard,
        opt_state=opt_state_shardings(opt_state, p_shard, runtime.mesh),
        skipped=replicated, consec_skipped=replicated,
    )
    train_step = make_train_step(
        loss_fn, optimizer, runtime, shardings, donate=True
    )
    key = jax.eval_shape(lambda: jax.random.key(0))
    args = (state, (text, image), key)
    in_sh = (shardings,
             (runtime.data_sharding, runtime.data_sharding), replicated)
    out_avals = jax.eval_shape(train_step, *args)
    out_sh = (shardings, replicated)

    arg_paths, in_expected = _flat_paths_and_specs(args, in_sh)
    out_paths, out_expected = _flat_paths_and_specs(out_avals, out_sh)

    # parameter leaves sit right after TrainState.step in the flattened
    # argument list (NamedTuple field order) — assert instead of trusting
    n_params = len(jax.tree_util.tree_leaves(params))
    assert arg_paths[1].endswith(
        jax.tree_util.keystr(
            jax.tree_util.tree_flatten_with_path(params)[0][0][0]
        )
    ), "TrainState flatten order changed — fix the param arg offsets"
    intents = []
    for i, rep in enumerate(params_spec_reports(params, runtime.mesh)):
        rep = dict(rep)
        rep["arg"] = 1 + i
        intents.append(rep)

    return ShardEntry(
        name=f"train.{name}",
        path=_STEP_PATH,
        symbol="make_train_step",
        mesh_axes={kind: 2},
        lower=lambda: train_step.lower(*args),
        partitioned=True,
        arg_paths=arg_paths,
        in_shardings=in_expected,
        out_paths=out_paths,
        out_shardings=out_expected,
        param_intents=tuple(intents),
    )


def build_train_entries() -> List[ShardEntry]:
    """The seven mesh-kind train entries alone — the multichip dryrun's
    provenance cross-check audits exactly these (__graft_entry__.py)."""
    return [
        _train_shard_entry(name, kind, model_kw, moe)
        for name, kind, model_kw, moe in MESH_KINDS
    ]


def build_serving_entries() -> List[ShardEntry]:
    """Every ``serving.*`` jit the trace registry declares, lowered as-is
    (signature 0 — collective structure is signature-independent, the
    same rationale as the trace stage's donation audit)."""
    from lint.trace.registry import build_entry_points as trace_entries

    out: List[ShardEntry] = []
    for ep in trace_entries():
        if not ep.name.startswith("serving.") or ep.lower is None:
            continue
        sig = ep.signatures[0]
        out.append(ShardEntry(
            name=ep.name,
            path=ep.path,
            symbol=ep.symbol,
            mesh_axes={},
            lower=(lambda ep=ep, sig=sig: ep.lower(*sig.args)),
            partitioned=False,
        ))
    return out


def build_entry_points() -> List[ShardEntry]:
    return build_train_entries() + build_serving_entries()
