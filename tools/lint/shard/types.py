"""Shard-stage data types: mesh-aware entry-point registry records.

Deliberately jax-free (the trace-stage ``types.py`` pattern): a registry
module — the repo's ``tools/lint/shard/registry.py`` or a test fixture —
imports this to DECLARE its entries; all lowering/compiling lives in
``audit.py``.

A :class:`ShardEntry` names one jitted program together with the mesh it
runs under and everything the sharding audit needs to judge it:

* ``lower`` is a zero-argument thunk returning the ``jax.stages.Lowered``
  program (the thunk owns arg construction and any ambient-mesh
  activation, so building the entry list stays cheap until the audit
  actually runs);
* ``partitioned`` asks the audit to ALSO compile the lowered program and
  count collectives in the post-SPMD-partitioning HLO — the ground truth
  for multi-device meshes, where GSPMD inserts collectives the source
  never wrote. Single-device entries skip the compile: partitioning is
  the identity there, and the PRE-partitioning StableHLO is where an
  explicit collective (a shard_map psum) cannot be elided away;
* ``arg_paths``/``in_shardings`` (and the ``out_*`` twins) are the
  flattened per-argument tree paths and EXPECTED HLO sharding strings
  the registry derives from ``parallel/sharding.py`` — the audit
  compares them 1:1 against the ``mhlo.sharding`` attributes of the
  lowered ``@main`` signature (DTL152). Empty sequences skip the check
  (the 1-device serving entries);
* ``param_intents`` is the :func:`parallel.sharding.spec_report` list
  for the parameter leaves (with ``"arg"`` indices into the flattened
  argument list), feeding the DTL153 accidental-replication check.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Mapping, Optional, Sequence


@dataclass(frozen=True)
class ShardEntry:
    """One registered program under one named mesh."""

    name: str
    path: str                       # repo-relative file (finding anchor)
    symbol: str                     # def name, for line lookup
    mesh_axes: Mapping[str, int]    # {} for plain 1-device jits
    lower: Callable[[], Any]        # thunk -> jax.stages.Lowered
    partitioned: bool = False       # compile & count post-SPMD collectives
    arg_paths: Sequence[str] = ()
    in_shardings: Sequence[Optional[str]] = ()
    out_paths: Sequence[str] = ()
    out_shardings: Sequence[Optional[str]] = ()
    param_intents: Sequence[Dict[str, Any]] = field(default_factory=tuple)
