"""dalle-tpu-lint, stage 3: mesh-aware sharding & collective audit
(``--shard``).

The AST stage (DTL0xx) checks what the source says; the trace stage
(DTL1xx, ``--trace``) checks the program XLA gets on one device. This
stage checks what the program COSTS on a mesh: every registered entry
point (``registry.py``: ``make_train_step`` under each of the six mesh
kinds from ``parallel/mesh.py``, plus every serving jit under its
current 1-device placement) is lowered over a host-platform device mesh
— and, for multi-device meshes, compiled on host CPU so the
post-SPMD-partitioning HLO is inspectable — then audited against the
committed ``tools/shard_contracts.json``. The failure modes this
catches are invisible in source and only show up as HBM blowups or
collective storms at run time: an accidentally replicated weight, a
hidden resharding copy, an unbudgeted all-gather.

Finding codes (docs/DESIGN.md §11.2):

=========  ==================================================================
DTL151     per-entry collective budget by op kind (all-gather / all-reduce
           / reduce-scatter / collective-permute / all-to-all): a count
           over the committed budget, or a kind the contract does not
           list at all — the silent-resharding bug class caught at lint
           time. Serving entries commit the "no collectives in serving"
           baseline ROADMAP item 1 will consciously renegotiate
DTL152     in/out sharding-spec contract: the lowered program's actual
           ``mhlo.sharding`` arg/result attributes vs the specs
           ``parallel/sharding.py:params_shardings`` derives (the
           ``:lowered`` anchor — drift between the rule engine and what
           GSPMD is handed lives in CODE and survives --emit-contract),
           and the derived specs/digests vs the committed contract (the
           ``:contract`` anchor — cleared by an intentional re-emit)
DTL153     accidental replication: a parameter the rules declare sharded
           but whose lowered sharding is fully replicated — the fsdp/tp
           memory story is fiction for that parameter. Lives in code;
           --emit-contract cannot clear it
DTL154     in-program sharding-constraint sites (``custom_call @Sharding``
           net of shard_map boundary markers) over the entry's budget —
           each one a potential device-to-device reshard copy not
           attributable to a declared spec boundary
DTL155     registry <-> contract 1:1 with stale-entry failure (the
           DTL101/102 mirror): an unregistered contract entry or an
           uncommitted registry entry both fail ``--check``
=========  ==================================================================

Like the trace stage this package imports jax AND the audited package —
``tools/lint/__init__.py`` must never import it; ``tools/lint.py``
loads it only under ``--shard`` (forcing an 8-device host platform
first). Findings flow through the same suppression/baseline machinery
and compose with the other stages in one exit code. ``--emit-contract``
regenerates the contract (the blessed-update workflow; how to
renegotiate the serving collective budget when multi-chip serving
lands is documented in docs/DESIGN.md §11.2).
"""

from __future__ import annotations

from .audit import (
    audit_shard_entry,
    check_reports,
    compiled_collectives,
    emit_contract,
    load_contract,
    lowered_collectives,
    parse_main_shardings,
    reshard_constraints,
    run_shard,
    shard_reports_only,
)
from .types import ShardEntry

__all__ = [
    "ShardEntry",
    "audit_shard_entry",
    "check_reports",
    "compiled_collectives",
    "emit_contract",
    "load_contract",
    "lowered_collectives",
    "parse_main_shardings",
    "reshard_constraints",
    "run_shard",
    "shard_reports_only",
]
