"""DTL031-033: fault-site cross-reference.

The fault registry (utils/faults.py) is only as good as its 1:1 mapping
between registered sites, production take-sites, and the drills that
exercise them. Runtime validation (the ``DALLE_TPU_FAULTS`` env parser)
catches a typo'd site only when someone runs that exact drill; this
checker closes the loop statically:

* **DTL031** — a ``FAULTS.take/maybe_raise/value/arm("...")`` literal
  that is not in ``KNOWN_SITES``: armed, it would silently inject
  nothing.
* **DTL032** — a ``KNOWN_SITES`` entry with no take/maybe_raise/value
  call in the scanned package: a dead registry entry (the failure it
  models can no longer be injected anywhere).
* **DTL033** — a ``KNOWN_SITES`` entry never exercised from the test/
  tool corpus (``tests/``, ``tools/``): the drill exists but nobody
  runs it. A site counts as exercised when its exact name — or a
  ``site=N`` env-spec fragment — appears as a string literal (f-string
  fragments included, so ``f"nan_at_step={k}"`` in an e2e env counts).

``KNOWN_SITES``/``_VALUE_SITES`` are AST-extracted from the registry
module, never imported — the linter stays jax-free and instant.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Sequence, Set, Tuple

from .core import (
    Finding,
    SourceFile,
    assign_lineno,
    load_files,
    parse_frozensets,
    str_const,
    string_fragments,
)

_TAKE_METHODS = {"take", "maybe_raise", "value"}
_ARM_METHODS = {"arm"}


def _site_calls(sf: SourceFile) -> List[Tuple[str, str, int]]:
    """(method, site-literal, line) for registry calls with a literal
    first argument."""
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr not in _TAKE_METHODS | _ARM_METHODS:
            continue
        # receiver must look like a fault registry (FAULTS / self.faults /
        # a FaultRegistry local) — keyed on the conventional names so
        # dict.get-style lookalikes never match
        recv = fn.value
        recv_name = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else ""
        )
        if "fault" not in recv_name.lower():
            continue
        if not node.args:
            continue
        site = str_const(node.args[0])
        if site is not None:
            out.append((fn.attr, site, node.lineno))
    return out


def check(files: Sequence[SourceFile], config,
          full: bool = True) -> List[Finding]:
    fc = config.faults
    if fc is None:
        return []
    registry_ab = os.path.join(config.repo_root, fc.registry_path)
    sets = parse_frozensets(registry_ab, ["KNOWN_SITES", "_VALUE_SITES"])
    known: Set[str] = sets.get("KNOWN_SITES", set())
    if not known:
        return [Finding(
            "DTL031", fc.registry_path, 1,
            "could not extract KNOWN_SITES from the fault registry",
            anchor="KNOWN_SITES",
        )]
    registry_line = assign_lineno(registry_ab, "KNOWN_SITES")

    findings: List[Finding] = []
    taken: Dict[str, List[str]] = {}
    for sf in files:
        if sf.path == fc.registry_path:
            continue
        for method, site, line in _site_calls(sf):
            if site not in known:
                findings.append(Finding(
                    "DTL031", sf.path, line,
                    f"FAULTS.{method}({site!r}) names an unregistered "
                    f"fault site (KNOWN_SITES: "
                    f"{', '.join(sorted(known))})",
                    anchor=site,
                ))
            elif method in _TAKE_METHODS:
                taken.setdefault(site, []).append(f"{sf.path}:{line}")

    if not full:
        # the dead-site/undrilled-site directions need the whole package
        # in view; a narrowed path list would call every unseen site dead
        return findings

    # exercise corpus: tests/ + tools/ string literals
    corpus = load_files(config.repo_root, fc.exercise_roots, config.exclude)
    exercised: Set[str] = set()
    for sf in corpus:
        for s, _line in string_fragments(sf.tree):
            for site in known:
                if site in exercised:
                    continue
                if s == site or (site + "=") in s:
                    exercised.add(site)

    for site in sorted(known):
        if site not in taken:
            findings.append(Finding(
                "DTL032", fc.registry_path, registry_line,
                f"KNOWN_SITES entry {site!r} has no "
                f"take/maybe_raise/value site in the package — dead "
                f"registry entry (retire it or add the injection point)",
                anchor=site,
            ))
        if site not in exercised:
            findings.append(Finding(
                "DTL033", fc.registry_path, registry_line,
                f"KNOWN_SITES entry {site!r} is never exercised from "
                f"{'/'.join(fc.exercise_roots)} — add a drill (arm() in a "
                f"test or a DALLE_TPU_FAULTS spec in a tool) or retire "
                f"the site",
                anchor=site,
            ))
    return findings
