"""Framework core: findings, suppressions, baseline, the runner.

A *finding* is (code, path, line, message, anchor). The anchor is the
checker-chosen stable identity component (a telemetry name, a fault
site, ``ClassName.field``, ``function:construct``) so the baseline key
``path::code::anchor`` survives unrelated edits that shift line numbers
— the property a committed baseline needs to not rot.

Suppression is line-scoped and explicit: ``# dtl: disable=DTL011`` (or a
comma list) on the finding's line. There is deliberately no file-scoped
or next-line form — a suppression should sit on the construct it
excuses, where a reviewer sees both together.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*dtl:\s*disable=([A-Z0-9,\s]+)")


@dataclass(frozen=True)
class Finding:
    code: str
    path: str      # repo-relative posix path
    line: int
    message: str
    anchor: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.code}::{self.anchor}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return {
            "code": self.code, "path": self.path, "line": self.line,
            "message": self.message, "key": self.key,
        }


class SourceFile:
    """One parsed module plus its suppression map."""

    def __init__(self, path: str, abspath: str, source: str):
        self.path = path
        self.abspath = abspath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self._suppress: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self._suppress[i] = {
                    c.strip() for c in m.group(1).split(",") if c.strip()
                }

    def suppressed(self, line: int, code: str) -> bool:
        return code in self._suppress.get(line, ())


@dataclass
class LintResult:
    findings: List[Finding]            # live (reported) findings
    suppressed: List[Finding]          # silenced by inline comments
    baselined: List[Finding]           # silenced by the baseline file
    stale_baseline: List[str]          # baseline keys that matched nothing

    @property
    def clean(self) -> bool:
        return not self.findings


def load_files(repo_root: str, roots: Sequence[str],
               exclude: Sequence[str] = ()) -> List[SourceFile]:
    """Load and parse every .py file under ``roots`` (repo-relative files
    or directories), skipping ``exclude`` fnmatch patterns. Unparseable
    files raise — a syntax error is itself a broken tree."""
    out: List[SourceFile] = []
    seen: Set[str] = set()
    for root in roots:
        ab_root = root if os.path.isabs(root) else os.path.join(repo_root, root)
        ab_root = os.path.abspath(ab_root)
        if os.path.isfile(ab_root):
            # an explicitly named file is always scanned — exclude
            # patterns only prune directory walks (they keep fixture
            # corpora out of the DEFAULT roots, not out of a direct ask)
            pairs = [(os.path.relpath(ab_root, repo_root), ab_root)]
            walked = False
        else:
            pairs = []
            walked = True
            for dirpath, dirnames, filenames in os.walk(ab_root):
                dirnames[:] = [
                    d for d in sorted(dirnames) if d != "__pycache__"
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        ab = os.path.join(dirpath, fn)
                        pairs.append((os.path.relpath(ab, repo_root), ab))
        for rel, ab in pairs:
            rel = rel.replace(os.sep, "/")
            if rel in seen:
                continue
            if walked and any(fnmatch.fnmatch(rel, pat) for pat in exclude):
                continue
            seen.add(rel)
            with open(ab, encoding="utf-8") as f:
                src = f.read()
            out.append(SourceFile(rel, ab, src))
    return out


def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """``{key: note}`` from the committed baseline JSON. The file is a
    list of ``{"key": ..., "note": ...}`` objects — every grandfathered
    finding must say WHY it is grandfathered."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)  # JSONDecodeError is a ValueError: CLI exit 2
    if not isinstance(data, list):
        raise ValueError(
            f"baseline {path}: want a JSON list of "
            f'{{"key": ..., "note": ...}} objects, got {type(data).__name__}'
        )
    out: Dict[str, str] = {}
    for i, entry in enumerate(data):
        if not isinstance(entry, dict) or "key" not in entry:
            raise ValueError(
                f"baseline {path}: entry {i} must be an object with a "
                f'"key" (and a justifying "note"), got {entry!r}'
            )
        out[entry["key"]] = entry.get("note", "")
    return out


def run_lint(config, paths: Optional[Sequence[str]] = None,
             checkers: Optional[Sequence[str]] = None,
             full: Optional[bool] = None,
             extra_findings: Optional[Sequence[Finding]] = None,
             stages: Optional[Set[str]] = None) -> LintResult:
    """Run the selected checkers (default: all configured) over ``paths``
    (default: the config's scan roots) and fold in suppressions and the
    baseline. ``full`` controls the registry-completeness directions
    (DTL032/033/042) — default: on exactly when scanning the full
    roots; fixture tests scanning explicit paths against their own
    miniature registries pass ``full=True``. ``extra_findings`` are
    pre-computed findings from other stages (the ``--trace`` jaxpr audit
    and/or the ``--shard`` mesh audit) merged in BEFORE suppression/
    baseline processing, so every stage shares one suppression syntax,
    one baseline file, and one exit code. ``stages`` names which extra
    stages actually RAN (subset of {"trace", "shard"}) — baseline
    staleness for a stage's codes is only judgeable when that stage ran;
    default: both when ``extra_findings`` is not None (one combined
    list), neither otherwise."""
    from . import fault_sites, layering, locks, names, purity

    registry = {
        "purity": purity.check,
        "layering": layering.check,
        "fault-sites": fault_sites.check,
        "telemetry-names": names.check,
        "locks": locks.check,
    }
    if checkers is None:
        selected = list(registry)
        if config.faults is None:
            selected.remove("fault-sites")
        if config.names is None:
            selected.remove("telemetry-names")
    else:
        unknown = set(checkers) - set(registry)
        if unknown:
            raise ValueError(
                f"unknown checkers {sorted(unknown)} "
                f"(known: {sorted(registry)})"
            )
        selected = list(checkers)

    # registry-completeness directions (dead fault sites, undocumented
    # registry names) are only meaningful over the full scan roots: a
    # narrowed path list would make every unseen use look "dead"
    if full is None:
        full = paths is None
    files = load_files(
        config.repo_root, paths or config.scan_roots, config.exclude
    )
    raw: List[Finding] = []
    for name in selected:
        raw.extend(registry[name](files, config, full=full))
    if extra_findings:
        raw.extend(extra_findings)
    raw.sort(key=lambda f: (f.path, f.line, f.code, f.anchor))
    # Uniquify colliding keys deterministically (source order): two `if`s
    # on traced values in one function share the anchor `fn:If`, and a
    # baseline entry must excuse exactly ONE violation, never a class of
    # them — the Nth same-anchor finding gets `#N`, so adding a new
    # violation of a baselined shape always surfaces at least one live
    # finding.
    occurrences: Dict[str, int] = {}
    uniq: List[Finding] = []
    for f in raw:
        n = occurrences.get(f.key, 0) + 1
        occurrences[f.key] = n
        if n > 1:
            f = Finding(f.code, f.path, f.line, f.message,
                        f"{f.anchor}#{n}")
        uniq.append(f)
    raw = uniq

    by_path = {f.path: f for f in files}
    if extra_findings:
        # trace-stage findings can anchor in files outside the AST scan
        # paths (a narrowed scan still audits every registered entry
        # point) — load those files on demand so their inline
        # `# dtl: disable=` suppressions keep working
        for f in extra_findings:
            if f.path not in by_path and f.path.endswith(".py"):
                try:
                    loaded = load_files(config.repo_root, [f.path])
                except (OSError, SyntaxError):
                    continue
                if loaded:
                    by_path[f.path] = loaded[0]
    baseline = load_baseline(
        None if config.baseline_path is None
        else os.path.join(config.repo_root, config.baseline_path)
    )
    live: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    matched_keys: Set[str] = set()
    for f in raw:
        sf = by_path.get(f.path)
        if sf is not None and sf.suppressed(f.line, f.code):
            suppressed.append(f)
        elif f.key in baseline:
            matched_keys.add(f.key)
            baselined.append(f)
        else:
            live.append(f)
    # staleness is only judgeable over the full scan roots — on a
    # narrowed path list, entries for unscanned files are merely unseen.
    # Same logic for STAGES: a DTL1xx (trace-stage) or DTL15x
    # (shard-stage) baseline key can only match when its stage ran (an
    # empty extra_findings list still means "ran, found nothing"), so an
    # AST-only scan must treat it as unseen, not stale, or a
    # legitimately baselined trace/shard finding would fail every plain
    # `--check` run.
    if stages is None:
        stages = ({"trace", "shard"} if extra_findings is not None
                  else set())

    def judgeable(key: str) -> bool:
        parts = key.split("::")
        code = parts[1] if len(parts) > 1 else ""
        if code.startswith("DTL15"):
            return "shard" in stages
        if code.startswith("DTL1"):
            return "trace" in stages
        return True

    stale = (
        sorted(k for k in set(baseline) - matched_keys if judgeable(k))
        if full else []
    )
    return LintResult(
        findings=live, suppressed=suppressed, baselined=baselined,
        stale_baseline=stale,
    )


# --------------------------------------------------------------- AST utils
# shared by the checkers; deliberately tiny and permissive — a helper
# returning None means "could not resolve statically", and checkers skip.


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.random.fold_in`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_prefix(node: ast.AST) -> Optional[str]:
    """The leading literal text of an f-string (empty string when it
    starts with an interpolation), None for non-f-strings."""
    if not isinstance(node, ast.JoinedStr):
        return None
    if node.values and isinstance(node.values[0], ast.Constant):
        v = node.values[0].value
        if isinstance(v, str):
            return v
    return ""


def string_fragments(tree: ast.AST) -> Iterable[Tuple[str, int]]:
    """Every string constant in the tree, f-string literal fragments
    included, DOCSTRINGS EXCLUDED — the corpus the fault-site exercise
    check greps. Docstrings don't count: documentation *mentioning* a
    drill (``DALLE_TPU_FAULTS="x=1" ...`` in a usage example) must not
    satisfy the cross-reference that the drill actually exists in code."""
    docstrings = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                docstrings.add(id(body[0].value))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and id(node) not in docstrings):
            yield node.value, getattr(node, "lineno", 0)


def parse_frozensets(path: str, names: Sequence[str]) -> Dict[str, Set[str]]:
    """AST-extract module-level ``NAME = frozenset({...})`` / set-literal
    string collections — how the linter reads the fault-site and
    telemetry-name registries without importing the package."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    want = set(names)
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name) or tgt.id not in want:
            continue
        value = node.value
        if (isinstance(value, ast.Call) and dotted_name(value.func) == "frozenset"
                and len(value.args) == 1):
            value = value.args[0]
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            strings = {
                s for el in value.elts
                for s in [str_const(el)] if s is not None
            }
            out[tgt.id] = strings
    return out


def assign_lineno(path: str, name: str) -> int:
    """Line of the module-level assignment to ``name`` (anchor for
    registry-level findings); 1 when absent."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return node.lineno
    return 1
