"""dalle-tpu-lint: AST-based invariant checker for this repository.

Six PRs in, the codebase's correctness rests on invariants that were
enforced only by convention or by one-off runtime checks: "telemetry is
host-side only" was a single source-grep test, fault-site names were
validated only when ``DALLE_TPU_FAULTS`` was parsed at runtime,
telemetry names were bare string literals scattered across the engine/
router/train paths, and the thread-safety of the replicated front door
depended on every future edit remembering which fields which lock
guards. This package makes those invariants machine-checked at review
time — before a single test runs — in the same spirit as the paper's
"static shapes everywhere" thesis (docs/DESIGN.md §1): the rules are
*data* (a layer map, a name registry, a ``_GUARDED_BY`` table), and one
small framework interprets them.

Five checkers, one finding-code block each (docs/DESIGN.md §11):

=========  ==================================================================
DTL011     jit purity: Python ``if``/``while`` on a traced value inside a
           ``jax.jit``/``pjit``/``shard_map``-wrapped function (retrace /
           trace-error hazard; ``is None`` structure checks are exempt)
DTL012     jit purity: host sync on a traced value (``.item()``,
           ``float()/int()/bool()``, ``np.asarray``/``np.array``)
DTL013     jit impurity: wall-clock / stdlib-RNG call inside jit-reachable
           code (``time.*``, ``random.*``, ``np.random.*`` — the value is
           frozen at trace time, a silent staleness bug)
DTL014     jit purity: closure over a mutable module-level container
           (list/dict/set global read inside a jitted function — already-
           cached traces ignore later mutation)
DTL021     import layering: a module imported something its declared layer
           forbids (host-side utils must be jax-free; ops must not import
           serving; library code must not import the CLI entrypoints)
DTL031     fault sites: a fault-registry call names a site that is not in
           ``KNOWN_SITES`` (would silently inject nothing)
DTL032     fault sites: a ``KNOWN_SITES`` entry has no take-site in the
           package (dead registry entry)
DTL033     fault sites: a ``KNOWN_SITES`` entry is never exercised by any
           test or tool (a drill nobody runs)
DTL041     telemetry names: a counter/gauge/histogram/span/event literal is
           not in the registry (``utils/telemetry_names.py``), or is
           registered under a different kind
DTL042     telemetry names: a registry entry is absent from the
           docs/DESIGN.md §9 name tables
DTL051     lock discipline: a field declared in a class's ``_GUARDED_BY``
           table is read/written outside a ``with self.<lock>`` block
           (``__init__`` and ``*_locked`` callee-convention methods exempt)
DTL052     lock-order cycle: two locks of one class are lexically acquired
           in opposite nesting orders somewhere (deadlock under the right
           interleaving), or a non-reentrant ``threading.Lock`` is
           re-acquired under itself; the acquisition graph is built from
           ``_GUARDED_BY`` keys plus ``__init__`` Lock/RLock/Condition
           assignments, across ALL methods (no ``*_locked`` exemption —
           ordering matters wherever it happens)
=========  ==================================================================

Suppression: append ``# dtl: disable=DTL0xx[,DTL0yy]`` to the finding's
line. Grandfathering: add the finding's stable key to the committed
baseline (``tools/lint_baseline.json``) with a justification note —
``--check`` ignores baselined findings but reports stale entries.

Stdlib-``ast`` only, no third-party deps, never imports the package it
lints (so it runs in milliseconds, jax-free, anywhere). The exceptions
are the optional later stages: ``tools/lint/trace/`` (``lint.py
--trace``, DTL1xx codes) traces the registered jit entry points to
ClosedJaxprs (abstract avals, CPU, no execution) and checks
compile-signature budgets, buffer donation/aliasing, host syncs, and
static HBM footprints against the committed
``tools/trace_contracts.json``; ``tools/lint/shard/`` (``lint.py
--shard``, DTL15x codes) lowers the train step under each of the six
mesh kinds over a forced multi-device host platform and audits
collective budgets, sharding specs, accidental replication, and
reshard constraints against ``tools/shard_contracts.json``. Both
import jax and the package, so this package's ``__init__`` must never
import them — the CLI loads them on demand, and their findings share
the suppression/baseline machinery here.
"""

from __future__ import annotations

from .core import Finding, LintResult, SourceFile, load_files, run_lint
from .config import (
    FaultConfig,
    LayerRule,
    LintConfig,
    NamesConfig,
    default_config,
)

__all__ = [
    "Finding",
    "LintResult",
    "SourceFile",
    "LintConfig",
    "LayerRule",
    "FaultConfig",
    "NamesConfig",
    "default_config",
    "load_files",
    "run_lint",
]
