"""Lint configuration: the invariants are DATA, this module declares them.

Everything a checker needs to know about *this* repository lives here —
the scan roots, the import-layer map, where the fault-site registry and
the telemetry-name registry live — so the checkers themselves stay
generic and the fixture tests can swap in miniature configs
(tests/test_static_analysis.py builds configs pointing at
tests/fixtures_lint/). ``default_config(repo_root)`` is the one the CLI
and the release gates run.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class LayerRule:
    """One import-layering constraint: files matching ``files`` (fnmatch
    patterns or directory prefixes, repo-relative posix paths) must not
    import any module whose dotted path starts with an entry of
    ``forbid`` (matched on dot boundaries; relative imports are resolved
    against the file's package path first)."""

    name: str
    files: Tuple[str, ...]
    forbid: Tuple[str, ...]
    why: str = ""


@dataclass(frozen=True)
class FaultConfig:
    """Fault-site cross-reference inputs. ``registry_path`` is AST-parsed
    for the ``KNOWN_SITES``/``_VALUE_SITES`` frozensets (the checker never
    imports the package); ``exercise_roots`` are the test/tool corpora a
    site must appear in (as an exact string literal, or inside a
    ``site=N`` env-spec fragment) to count as drilled."""

    registry_path: str
    exercise_roots: Tuple[str, ...]


@dataclass(frozen=True)
class NamesConfig:
    """Telemetry-name registry inputs. ``registry_path`` is AST-parsed for
    the per-kind frozensets (SPANS/EVENTS/COUNTERS/GAUGES/HISTOGRAMS);
    ``doc_path``/``doc_section`` locate the DESIGN.md name tables every
    registered name must appear in."""

    registry_path: str
    doc_path: str
    doc_section: str = "## 9."


@dataclass(frozen=True)
class TraceConfig:
    """Trace-stage (``--trace``) inputs: the entry-point registry module
    (imported by file path — the one module of the linter that DOES
    import jax and the package, so it is loaded only on demand) and the
    committed contract file the audit gates against."""

    registry_path: str = "tools/lint/trace/registry.py"
    contract_path: str = "tools/trace_contracts.json"


@dataclass(frozen=True)
class ShardConfig:
    """Shard-stage (``--shard``) inputs: the mesh-aware entry-point
    registry module (imported by file path, jax + package on demand —
    the trace-stage pattern) and the committed contract file the
    collective/sharding audit gates against."""

    registry_path: str = "tools/lint/shard/registry.py"
    contract_path: str = "tools/shard_contracts.json"


@dataclass(frozen=True)
class LintConfig:
    repo_root: str
    # files/dirs (repo-relative) the checkers scan by default
    scan_roots: Tuple[str, ...]
    # fnmatch patterns (repo-relative) excluded from any scan
    exclude: Tuple[str, ...]
    layer_rules: Tuple[LayerRule, ...]
    faults: Optional[FaultConfig]
    names: Optional[NamesConfig]
    baseline_path: Optional[str] = None
    trace: Optional[TraceConfig] = None
    shard: Optional[ShardConfig] = None


# the host-side observability/resilience layer: imported from loader
# threads, signal handlers, and the serving hot loop — a jax import here
# is a latent device sync (and a measurement that destroys what it
# measures; utils/telemetry.py module docstring). Generalizes the old
# source-grep pin in tests/test_telemetry.py.
_HOST_ONLY_FILES = (
    "dalle_pytorch_tpu/utils/telemetry.py",
    "dalle_pytorch_tpu/utils/telemetry_names.py",
    "dalle_pytorch_tpu/utils/metrics.py",
    "dalle_pytorch_tpu/utils/faults.py",
    "dalle_pytorch_tpu/utils/resilience.py",
    "dalle_pytorch_tpu/utils/vitals.py",
)

_JAX_STACK = ("jax", "jaxlib", "flax", "optax")


def default_layer_rules() -> Tuple[LayerRule, ...]:
    return (
        LayerRule(
            name="host-only-utils",
            files=_HOST_ONLY_FILES,
            forbid=_JAX_STACK
            + (
                "dalle_pytorch_tpu.serving",
                "dalle_pytorch_tpu.models",
                "dalle_pytorch_tpu.ops",
                "dalle_pytorch_tpu.parallel",
                "dalle_pytorch_tpu.data",
            ),
            why="telemetry/metrics/faults/resilience are host-side only: "
                "no jax (device syncs), no package layers above utils "
                "(the serving Clock protocol is duck-typed on purpose)",
        ),
        LayerRule(
            name="ops-below-serving",
            files=("dalle_pytorch_tpu/ops/*.py",),
            forbid=("dalle_pytorch_tpu.serving",),
            why="kernels/cache primitives are the bottom layer; the "
                "serving engine composes them, never the reverse",
        ),
        LayerRule(
            name="library-below-entrypoints",
            files=("dalle_pytorch_tpu/*.py", "dalle_pytorch_tpu/*/*.py"),
            forbid=("train_dalle", "train_vae", "train_clip",
                    "generate", "bench"),
            why="library code must not import the CLI entrypoints "
                "(script-level side effects, circular bootstrap)",
        ),
    )


def default_config(repo_root: str) -> LintConfig:
    repo_root = os.path.abspath(repo_root)
    return LintConfig(
        repo_root=repo_root,
        scan_roots=(
            "dalle_pytorch_tpu",
            "train_dalle.py",
            "train_vae.py",
            "train_clip.py",
            "generate.py",
            "bench.py",
        ),
        exclude=(
            "*/__pycache__/*",
            "tests/fixtures_lint/*",
            # the linter's own sources are full of deliberate bad
            # examples (checker docstrings, fixture snippets) — they are
            # neither scan targets nor a drill corpus
            "tools/lint.py",
            "tools/lint/*",
        ),
        layer_rules=default_layer_rules(),
        faults=FaultConfig(
            registry_path="dalle_pytorch_tpu/utils/faults.py",
            exercise_roots=("tests", "tools"),
        ),
        names=NamesConfig(
            registry_path="dalle_pytorch_tpu/utils/telemetry_names.py",
            doc_path="docs/DESIGN.md",
            doc_section="## 9.",
        ),
        baseline_path="tools/lint_baseline.json",
        trace=TraceConfig(),
        shard=ShardConfig(),
    )
