"""DTL051/DTL052: lock discipline via per-class ``_GUARDED_BY`` tables.

A class declares which of its fields its lock guards::

    class Router:
        _GUARDED_BY = {"_lock": ("_queue", "results", "_live")}

and this checker enforces, lexically, that every ``self.<field>`` access
for a guarded field happens inside a ``with self.<lock>:`` block. The
table is the contract future edits can't silently forget — exactly the
failure mode of "PR 6's thread-safety depends on remembering which
fields the lock guards".

Conventions (each one is a reviewed, visible signal at the def site):

* ``__init__`` is exempt — the object is not yet shared.
* Methods whose name ends in ``_locked`` are exempt — the caller-holds-
  the-lock convention this codebase already uses (``_drain_locked``).
  Such methods must only be called with the lock held; giving them the
  suffix is the declaration.
* Nested functions/lambdas inherit the lexical lock state of their
  definition site (a sort key lambda inside a locked region counts as
  locked; a callback stored for later does not get extra analysis —
  keep those out of guarded classes).
* Reads and writes are treated identically: torn reads on a field the
  table says is guarded are findings too.

DTL052 — lock-order cycle detection — rides the same scan: every lock a
class owns (a ``_GUARDED_BY`` key, or a ``self.<attr> =
threading.Lock()/RLock()/Condition()`` assignment in ``__init__``)
becomes a graph node, and every LEXICALLY nested acquisition (``with
self._b:`` inside a ``with self._a:`` region, across all methods —
``__init__`` and ``*_locked`` included, since ordering matters wherever
it happens) adds an ``a -> b`` edge. Any cycle — two methods acquiring
two locks in opposite orders — is a deadlock waiting for the right
thread interleaving, and a finding. A self-edge (``with self._a``
nested under itself) is a finding only for a non-reentrant
``threading.Lock``: re-acquiring an RLock is this codebase's sanctioned
pattern (Router's fleet_occupancy reentry), re-acquiring a plain Lock
is a guaranteed single-thread deadlock. Lexical scope means
call-through cycles (method A holds lock 1 and CALLS something
acquiring lock 2) are out of scope — keep cross-object calls out of
locked regions, which DTL051's field table already pushes toward.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, SourceFile, str_const


def _guarded_table(
    cls: ast.ClassDef,
) -> Tuple[Optional[Dict[str, Tuple[str, ...]]], Optional[int]]:
    """(table, None) for a well-formed declaration, (None, None) when the
    class declares nothing, (None, lineno) for a MALFORMED table — the
    caller must report that loudly: a table that silently parses to
    nothing disables exactly the check it exists to declare."""
    for node in cls.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return None, node.lineno
        table: Dict[str, Tuple[str, ...]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            lock = str_const(k) if k is not None else None
            if lock is None:
                return None, node.lineno
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                fields = tuple(
                    s for el in v.elts for s in [str_const(el)]
                    if s is not None
                )
                if len(fields) != len(v.elts):
                    return None, node.lineno
            else:
                s = str_const(v)
                if s is None:
                    return None, node.lineno
                fields = (s,)
            table[lock] = fields
        if not table:
            return None, node.lineno
        return table, None
    return None, None


def _init_assigned_attrs(cls: ast.ClassDef) -> Optional[set]:
    """self.<attr> names assigned anywhere in __init__ (None when the
    class has no __init__ of its own — inherited init, can't judge)."""
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            return {
                n.attr
                for n in ast.walk(node)
                if isinstance(n, ast.Attribute)
                and not isinstance(n.ctx, ast.Load)
                and isinstance(n.value, ast.Name) and n.value.id == "self"
            }
    return None


def _is_self_attr(node: ast.AST, attr: str) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}


def _lock_kinds(cls: ast.ClassDef,
                table: Optional[Dict[str, Tuple[str, ...]]]) -> Dict[str, Optional[str]]:
    """attr -> constructor kind for every lock this class owns: the
    ``_GUARDED_BY`` keys (kind unknown until the ctor is seen) plus any
    ``self.<attr> = threading.Lock()/RLock()/Condition()`` in
    ``__init__`` — so DTL052 covers lock-owning classes that never
    declared a field table."""
    from .core import dotted_name

    kinds: Dict[str, Optional[str]] = {
        lock: None for lock in (table or {})
    }
    for node in cls.body:
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "__init__"):
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign):
                continue
            if not isinstance(stmt.value, ast.Call):
                continue
            kind = _LOCK_CTORS.get(dotted_name(stmt.value.func) or "")
            if kind is None:
                continue
            for tgt in stmt.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    kinds[tgt.attr] = kind
    return kinds


def _collect_order_edges(
    cls: ast.ClassDef,
    lock_attrs: Sequence[str],
    edges: Dict[Tuple[str, str], Tuple[int, str]],
) -> None:
    """Record every lexically nested acquisition pair ``held -> acquired``
    across ALL methods of ``cls`` (first site wins per pair; the site is
    the inner ``with``'s line). Multi-item ``with self._a, self._b:``
    acquires left-to-right, so later items see earlier ones as held."""
    locks = set(lock_attrs)

    def visit(node: ast.AST, held: Tuple[str, ...],
              method: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def merely DEFINED under a lock executes later,
            # without it — its acquisitions are not ordered edges (a
            # lambda can't contain a `with`, so only defs matter). This
            # deliberately differs from DTL051's inherit-the-lock-state
            # rule: there the risk is a torn access IF it runs locked,
            # here a phantom edge would report a deadlock-free class.
            for stmt in node.body:
                visit(stmt, (), method)
            return
        if isinstance(node, ast.With):
            inner = list(held)
            for item in node.items:
                visit(item.context_expr, tuple(inner), method)
                acquired = next(
                    (lk for lk in locks
                     if _is_self_attr(item.context_expr, lk)), None
                )
                if acquired is not None:
                    for h in inner:
                        key = (h, acquired)
                        if key not in edges:
                            edges[key] = (node.lineno, method)
                    inner.append(acquired)
            for stmt in node.body:
                visit(stmt, tuple(inner), method)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held, method)

    for method in cls.body:
        if isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in method.body:
                visit(stmt, (), method.name)


def _cycle_findings(sf: SourceFile, cls: ast.ClassDef,
                    kinds: Dict[str, Optional[str]],
                    edges: Dict[Tuple[str, str], Tuple[int, str]],
                    findings: List[Finding]) -> None:
    """Tarjan-free SCC-lite: the graphs are tiny (a class owns a handful
    of locks), so find cycles by checking mutual reachability per pair
    and self-edges directly."""
    adj: Dict[str, set] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            for m in adj.get(n, ()):
                if m == dst:
                    return True
                if m not in seen:
                    seen.add(m)
                    stack.append(m)
        return False

    # self-deadlock: re-acquiring a NON-reentrant lock under itself
    for (a, b), (line, method) in sorted(edges.items(),
                                         key=lambda kv: kv[1][0]):
        if a == b and kinds.get(a) == "Lock":
            findings.append(Finding(
                "DTL052", sf.path, line,
                f"{cls.name}.{method} re-acquires non-reentrant lock "
                f"`self.{a}` (threading.Lock) while already holding it — "
                f"a single-thread deadlock; use an RLock only if "
                f"reentrancy is truly intended",
                anchor=f"{cls.name}:{a}->{a}",
            ))

    # order-inversion cycles: report each unordered lock pair once, at
    # the earliest edge site that participates in the cycle
    reported = set()
    for (a, b), (line, method) in sorted(edges.items(),
                                         key=lambda kv: kv[1][0]):
        if a == b:
            continue
        pair = tuple(sorted((a, b)))
        if pair in reported:
            continue
        if reaches(b, a):
            reported.add(pair)
            findings.append(Finding(
                "DTL052", sf.path, line,
                f"{cls.name} acquires `self.{b}` while holding "
                f"`self.{a}` (in {method}) AND `self.{a}` is reachable "
                f"while holding `self.{b}` elsewhere — a lock-order "
                f"cycle deadlocks under the right thread interleaving; "
                f"pick ONE order and declare it",
                anchor=f"{cls.name}:{'->'.join(pair)}",
            ))


def check(files: Sequence[SourceFile], config,
          full: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            table, bad_line = _guarded_table(cls)
            if bad_line is not None:
                findings.append(Finding(
                    "DTL051", sf.path, bad_line,
                    f"{cls.name}._GUARDED_BY is malformed (want a dict "
                    f"of lock-attr string -> tuple of field-name "
                    f"strings) — a table that parses to nothing silently "
                    f"disables the check it declares",
                    anchor=f"{cls.name}:_GUARDED_BY",
                ))
                continue
            # DTL052: lock-order cycles — any class that OWNS locks is in
            # scope, _GUARDED_BY table or not
            kinds = _lock_kinds(cls, table)
            if kinds:
                edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
                _collect_order_edges(cls, list(kinds), edges)
                _cycle_findings(sf, cls, kinds, edges, findings)
            if not table:
                continue
            field_to_lock = {
                f: lock for lock, fields in table.items() for f in fields
            }
            # a guarded field __init__ never assigns is almost certainly
            # a typo — the misspelled name would guard nothing, forever
            init_attrs = _init_assigned_attrs(cls)
            if init_attrs is not None:
                for f in sorted(set(field_to_lock) - init_attrs):
                    findings.append(Finding(
                        "DTL051", sf.path, cls.lineno,
                        f"{cls.name}._GUARDED_BY declares field "
                        f"`{f}` that __init__ never assigns — typo'd "
                        f"names guard nothing",
                        anchor=f"{cls.name}:_GUARDED_BY:{f}",
                    ))
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__" or method.name.endswith("_locked"):
                    continue
                _walk_method(sf, cls, method, field_to_lock, findings)
    return findings


def _walk_method(sf: SourceFile, cls: ast.ClassDef, method: ast.FunctionDef,
                 field_to_lock: Dict[str, str],
                 findings: List[Finding]) -> None:
    locks = set(field_to_lock.values())

    def visit(node: ast.AST, held: frozenset) -> None:
        if isinstance(node, ast.With):
            acquired = {
                lock for item in node.items
                for lock in locks
                if _is_self_attr(item.context_expr, lock)
            }
            for item in node.items:
                visit(item.context_expr, held)
            for stmt in node.body:
                visit(stmt, held | frozenset(acquired))
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in field_to_lock):
            lock = field_to_lock[node.attr]
            if lock not in held:
                findings.append(Finding(
                    "DTL051", sf.path, node.lineno,
                    f"{cls.name}.{method.name} accesses guarded field "
                    f"`self.{node.attr}` outside `with self.{lock}` "
                    f"(declare the method *_locked if the caller holds "
                    f"the lock)",
                    anchor=f"{cls.name}.{method.name}:{node.attr}",
                ))
            # still recurse into the value chain? self.<field>.x — the
            # access itself was the finding; no deeper guarded attrs here
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in method.body:
        visit(stmt, frozenset())
