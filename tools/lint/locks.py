"""DTL051: lock discipline via per-class ``_GUARDED_BY`` tables.

A class declares which of its fields its lock guards::

    class Router:
        _GUARDED_BY = {"_lock": ("_queue", "results", "_live")}

and this checker enforces, lexically, that every ``self.<field>`` access
for a guarded field happens inside a ``with self.<lock>:`` block. The
table is the contract future edits can't silently forget — exactly the
failure mode of "PR 6's thread-safety depends on remembering which
fields the lock guards".

Conventions (each one is a reviewed, visible signal at the def site):

* ``__init__`` is exempt — the object is not yet shared.
* Methods whose name ends in ``_locked`` are exempt — the caller-holds-
  the-lock convention this codebase already uses (``_drain_locked``).
  Such methods must only be called with the lock held; giving them the
  suffix is the declaration.
* Nested functions/lambdas inherit the lexical lock state of their
  definition site (a sort key lambda inside a locked region counts as
  locked; a callback stored for later does not get extra analysis —
  keep those out of guarded classes).
* Reads and writes are treated identically: torn reads on a field the
  table says is guarded are findings too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, SourceFile, str_const


def _guarded_table(
    cls: ast.ClassDef,
) -> Tuple[Optional[Dict[str, Tuple[str, ...]]], Optional[int]]:
    """(table, None) for a well-formed declaration, (None, None) when the
    class declares nothing, (None, lineno) for a MALFORMED table — the
    caller must report that loudly: a table that silently parses to
    nothing disables exactly the check it exists to declare."""
    for node in cls.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return None, node.lineno
        table: Dict[str, Tuple[str, ...]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            lock = str_const(k) if k is not None else None
            if lock is None:
                return None, node.lineno
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                fields = tuple(
                    s for el in v.elts for s in [str_const(el)]
                    if s is not None
                )
                if len(fields) != len(v.elts):
                    return None, node.lineno
            else:
                s = str_const(v)
                if s is None:
                    return None, node.lineno
                fields = (s,)
            table[lock] = fields
        if not table:
            return None, node.lineno
        return table, None
    return None, None


def _init_assigned_attrs(cls: ast.ClassDef) -> Optional[set]:
    """self.<attr> names assigned anywhere in __init__ (None when the
    class has no __init__ of its own — inherited init, can't judge)."""
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            return {
                n.attr
                for n in ast.walk(node)
                if isinstance(n, ast.Attribute)
                and not isinstance(n.ctx, ast.Load)
                and isinstance(n.value, ast.Name) and n.value.id == "self"
            }
    return None


def _is_self_attr(node: ast.AST, attr: str) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def check(files: Sequence[SourceFile], config,
          full: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            table, bad_line = _guarded_table(cls)
            if bad_line is not None:
                findings.append(Finding(
                    "DTL051", sf.path, bad_line,
                    f"{cls.name}._GUARDED_BY is malformed (want a dict "
                    f"of lock-attr string -> tuple of field-name "
                    f"strings) — a table that parses to nothing silently "
                    f"disables the check it declares",
                    anchor=f"{cls.name}:_GUARDED_BY",
                ))
                continue
            if not table:
                continue
            field_to_lock = {
                f: lock for lock, fields in table.items() for f in fields
            }
            # a guarded field __init__ never assigns is almost certainly
            # a typo — the misspelled name would guard nothing, forever
            init_attrs = _init_assigned_attrs(cls)
            if init_attrs is not None:
                for f in sorted(set(field_to_lock) - init_attrs):
                    findings.append(Finding(
                        "DTL051", sf.path, cls.lineno,
                        f"{cls.name}._GUARDED_BY declares field "
                        f"`{f}` that __init__ never assigns — typo'd "
                        f"names guard nothing",
                        anchor=f"{cls.name}:_GUARDED_BY:{f}",
                    ))
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__" or method.name.endswith("_locked"):
                    continue
                _walk_method(sf, cls, method, field_to_lock, findings)
    return findings


def _walk_method(sf: SourceFile, cls: ast.ClassDef, method: ast.FunctionDef,
                 field_to_lock: Dict[str, str],
                 findings: List[Finding]) -> None:
    locks = set(field_to_lock.values())

    def visit(node: ast.AST, held: frozenset) -> None:
        if isinstance(node, ast.With):
            acquired = {
                lock for item in node.items
                for lock in locks
                if _is_self_attr(item.context_expr, lock)
            }
            for item in node.items:
                visit(item.context_expr, held)
            for stmt in node.body:
                visit(stmt, held | frozenset(acquired))
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in field_to_lock):
            lock = field_to_lock[node.attr]
            if lock not in held:
                findings.append(Finding(
                    "DTL051", sf.path, node.lineno,
                    f"{cls.name}.{method.name} accesses guarded field "
                    f"`self.{node.attr}` outside `with self.{lock}` "
                    f"(declare the method *_locked if the caller holds "
                    f"the lock)",
                    anchor=f"{cls.name}.{method.name}:{node.attr}",
                ))
            # still recurse into the value chain? self.<field>.x — the
            # access itself was the finding; no deeper guarded attrs here
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in method.body:
        visit(stmt, frozenset())
