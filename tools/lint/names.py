"""DTL041-042: telemetry-name registry cross-reference.

Every counter/gauge/histogram/span/event name in the package must come
from the single registry (``utils/telemetry_names.py``) — a typo'd
metric name is a series nobody's dashboard, bench mapping, or smoke gate
ever finds, failing silently forever. The registry is per-kind, so a
counter name used as a gauge is also a finding.

Checked call shapes (first positional argument):

* ``counters.inc/get(...)``, ``gauges.set/get(...)``,
  ``histograms.observe/get(...)`` — receiver's last attribute component
  must literally be ``counters``/``gauges``/``histograms`` (the module
  registries or an engine's ``self.counters`` child view);
* ``TELEMETRY.begin/span(...)`` (spans) and ``TELEMETRY.event(...)``.

Literal names must be registered exactly. f-strings with a literal head
(``f"serve.rejected.{reason.value}"``) must have a head that prefixes at
least one registered name of that kind — dynamic tails stay checkable at
the namespace level without enumerating runtime values. Histogram reads
additionally accept ``<span>_s`` for any registered span (the duration
histograms utils/telemetry.py derives automatically).

**DTL042** closes the docs loop: every registered name must appear in
the docs/DESIGN.md §9 name tables, so the registry, the code, and the
operator documentation cannot drift apart (`*` wildcards in the doc are
not honored — names are enumerated).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set

from .core import (
    Finding,
    SourceFile,
    assign_lineno,
    fstring_prefix,
    parse_frozensets,
    str_const,
)

_REGISTRY_SETS = ("SPANS", "EVENTS", "COUNTERS", "GAUGES", "HISTOGRAMS")

# receiver last-component -> (checked methods, registry kind)
_RECEIVERS = {
    "counters": ({"inc", "get"}, "COUNTERS"),
    "gauges": ({"set", "get"}, "GAUGES"),
    "histograms": ({"observe", "get"}, "HISTOGRAMS"),
}
_TELEMETRY_METHODS = {
    "begin": "SPANS",
    "span": "SPANS",
    "event": "EVENTS",
}


def _receiver_tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _load_registry(path: str) -> Dict[str, Set[str]]:
    sets = parse_frozensets(path, _REGISTRY_SETS)
    return {k: sets.get(k, set()) for k in _REGISTRY_SETS}


def check(files: Sequence[SourceFile], config,
          full: bool = True) -> List[Finding]:
    nc = config.names
    if nc is None:
        return []
    registry_ab = os.path.join(config.repo_root, nc.registry_path)
    reg = _load_registry(registry_ab)
    all_names: Set[str] = set().union(*reg.values())
    if not all_names:
        return [Finding(
            "DTL041", nc.registry_path, 1,
            "could not extract any name sets from the telemetry-name "
            "registry", anchor="registry",
        )]
    # span-duration histograms are derived, not declared twice
    hist_names = reg["HISTOGRAMS"] | {s + "_s" for s in reg["SPANS"]}
    kind_names = dict(reg)
    kind_names["HISTOGRAMS"] = hist_names

    findings: List[Finding] = []
    for sf in files:
        if sf.path == nc.registry_path:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute) or not node.args:
                continue
            kind = None
            tail = _receiver_tail(fn.value)
            if tail in _RECEIVERS:
                methods, kind_key = _RECEIVERS[tail]
                if fn.attr in methods:
                    kind = kind_key
            elif tail == "TELEMETRY" and fn.attr in _TELEMETRY_METHODS:
                kind = _TELEMETRY_METHODS[fn.attr]
            if kind is None:
                continue
            arg = node.args[0]
            name = str_const(arg)
            valid = kind_names[kind]
            if name is not None:
                if name not in valid:
                    where = (f"registered as "
                             f"{', '.join(sorted(k for k, v in kind_names.items() if name in v))}"
                             if name in set().union(*kind_names.values())
                             else "not in the registry")
                    findings.append(Finding(
                        "DTL041", sf.path, node.lineno,
                        f"telemetry name {name!r} used as {kind.lower()[:-1]} "
                        f"is {where} — add it to "
                        f"{nc.registry_path} (and docs §9) or fix the typo",
                        anchor=f"{kind}:{name}",
                    ))
                continue
            prefix = fstring_prefix(arg)
            if prefix is None:
                continue  # a variable name: not statically checkable
            if not prefix:
                continue  # f-string with no literal head (e.g. f"{name}_s")
            if not any(v.startswith(prefix) for v in valid):
                findings.append(Finding(
                    "DTL041", sf.path, node.lineno,
                    f"dynamic telemetry name with head {prefix!r} matches "
                    f"no registered {kind.lower()} — register the expanded "
                    f"names or fix the namespace",
                    anchor=f"{kind}:{prefix}*",
                ))

    # DTL042: registry entries absent from the docs name tables (a
    # registry-completeness direction — full scans only, like DTL032/033)
    if not full:
        return findings
    doc_ab = os.path.join(config.repo_root, nc.doc_path)
    section = _doc_section(doc_ab, nc.doc_section)
    # documented = appears as a whole backtick-quoted token (optionally
    # with a label suffix, `name{replica=i}`). A raw substring test
    # would let a name that PREFIXES another documented name (router.drain
    # vs router.drained) pass undocumented.
    spans = set(re.findall(r"`([^`]+)`", section))
    reg_line = assign_lineno(registry_ab, "SPANS")

    def documented(name: str) -> bool:
        return name in spans or any(
            s.startswith(name + "{") for s in spans
        )

    for kind in _REGISTRY_SETS:
        for name in sorted(reg[kind]):
            if not documented(name):
                findings.append(Finding(
                    "DTL042", nc.registry_path, reg_line,
                    f"registered {kind.lower()[:-1]} {name!r} is not "
                    f"documented in {nc.doc_path} {nc.doc_section}* — "
                    f"add it to the name tables (backtick-quoted)",
                    anchor=name,
                ))
    return findings


def _doc_section(path: str, heading_prefix: str) -> str:
    """Text of the doc section whose heading starts with
    ``heading_prefix``, up to the next same-level heading."""
    if not os.path.exists(path):
        return ""
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    out: List[str] = []
    inside = False
    for line in lines:
        if line.startswith("## "):
            if inside:
                break
            inside = line.startswith(heading_prefix)
            continue
        if inside:
            out.append(line)
    return "\n".join(out)
