"""DTL011-014: jit purity / retrace-hazard checker.

Finds ``jax.jit`` / ``pjit`` / ``shard_map`` wrap sites (decorator form,
``partial(jax.jit, ...)`` form, and call form ``jit(fn)`` /
``shard_map(fn, ...)`` where ``fn`` resolves to a function in the same
module) and, inside the wrapped functions, flags host-level constructs
that are either trace errors waiting for the right input or silent
retrace/staleness hazards:

* **DTL011** — a Python ``if``/``while`` whose test references a traced
  value. Static arguments (``static_argnums``/``static_argnames``) and
  closure constants are excluded; ``x is None`` / ``x is not None``
  structure checks are exempt (None-vs-tracer is decided at trace time
  by design).
* **DTL012** — a host sync on a traced value: ``.item()``,
  ``float()/int()/bool()``, ``np.asarray``/``np.array``.
* **DTL013** — an impure host call (``time.*``, stdlib ``random.*``,
  ``np.random.*``) anywhere jit-reachable: its value is captured ONCE at
  trace time, so the code reads like it varies per call and doesn't.
  Applied to wrapped functions AND same-module functions they call
  (``jax.random.*`` is functional and exempt).
* **DTL014** — a read of a mutable module-level container (list/dict/set
  global) inside a wrapped function: cached traces ignore later
  mutation, the classic "I toggled the global and nothing changed" bug.

Taint tracking is deliberately lexical and shallow (parameters, then
single-assignment propagation; ``.shape``/``.dtype``/``.ndim`` reads are
untainted): the goal is review-time signal on real hazards, not a type
system. False positives get an inline ``# dtl: disable=`` with a reason,
which is itself documentation.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, dotted_name

_JIT_WRAPPERS = {"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"}
_SHARD_WRAPPERS = {"shard_map", "jax.shard_map",
                   "jax.experimental.shard_map.shard_map"}
_PARTIALS = {"partial", "functools.partial"}

# attribute reads that yield host-static metadata, never a tracer
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}

# dotted call prefixes that are impure at trace time (DTL013)
_IMPURE_PREFIXES = (
    "time.", "np.random.", "numpy.random.", "random.",
    "datetime.datetime.now", "datetime.date.today",
)
# ... except jax.random, which is functional
_PURE_PREFIXES = ("jax.random.",)

_HOST_CASTS = {"float", "int", "bool"}
_HOST_ARRAY_FNS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                   "onp.asarray", "onp.array"}


def _call_resolves_to(node: ast.AST, names: Set[str]) -> bool:
    d = dotted_name(node)
    return d is not None and d in names


class _WrapSite:
    def __init__(self, fn: ast.FunctionDef, static_idx: Set[int],
                 static_names: Set[str], kind: str):
        self.fn = fn
        self.static_idx = static_idx
        self.static_names = static_names
        self.kind = kind  # "jit" | "shard_map"


def _static_spec(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """static_argnums/static_argnames out of a jit(...) or
    partial(jax.jit, ...) call's keywords."""
    idx: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for el in elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    idx.add(el.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for el in elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.add(el.value)
    return idx, names


def _wrap_sites(tree: ast.AST) -> List[_WrapSite]:
    """All functions in the module wrapped by jit/pjit/shard_map —
    decorator, partial-decorator, or call form."""
    fns_by_name: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns_by_name.setdefault(node.name, []).append(node)

    sites: List[_WrapSite] = []
    seen: Set[int] = set()

    def add(fn: ast.FunctionDef, static_idx: Set[int],
            static_names: Set[str], kind: str) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        sites.append(_WrapSite(fn, static_idx, static_names, kind))

    # decorator form
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if _call_resolves_to(dec, _JIT_WRAPPERS):
                add(node, set(), set(), "jit")
            elif isinstance(dec, ast.Call):
                if _call_resolves_to(dec.func, _JIT_WRAPPERS):
                    idx, names = _static_spec(dec)
                    add(node, idx, names, "jit")
                elif (_call_resolves_to(dec.func, _PARTIALS) and dec.args
                      and _call_resolves_to(dec.args[0], _JIT_WRAPPERS)):
                    idx, names = _static_spec(dec)
                    add(node, idx, names, "jit")
                elif _call_resolves_to(dec.func, _SHARD_WRAPPERS) or (
                    _call_resolves_to(dec.func, _PARTIALS) and dec.args
                    and _call_resolves_to(dec.args[0], _SHARD_WRAPPERS)
                ):
                    add(node, set(), set(), "shard_map")

    # call form: jit(fn, ...) / shard_map(fn, ...) with fn a same-module def
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kind = None
        if _call_resolves_to(node.func, _JIT_WRAPPERS):
            kind = "jit"
        elif _call_resolves_to(node.func, _SHARD_WRAPPERS):
            kind = "shard_map"
        if kind is None or not node.args:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            for fn in fns_by_name.get(target.id, ()):
                idx, names = _static_spec(node) if kind == "jit" else (set(), set())
                add(fn, idx, names, kind)
    return sites


def _param_names(fn: ast.FunctionDef, static_idx: Set[int],
                 static_names: Set[str]) -> Set[str]:
    args = fn.args
    ordered = list(args.posonlyargs) + list(args.args)
    traced: Set[str] = set()
    for i, a in enumerate(ordered):
        if i in static_idx or a.arg in static_names or a.arg == "self":
            continue
        traced.add(a.arg)
    for a in args.kwonlyargs:
        if a.arg not in static_names:
            traced.add(a.arg)
    return traced


def _expr_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Does this expression (conservatively, lexically) carry a traced
    value? Static-metadata attribute reads break the chain."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d == "len":
            return False
        return any(_expr_tainted(a, tainted) for a in node.args) or any(
            _expr_tainted(kw.value, tainted) for kw in node.keywords
        ) or _expr_tainted(node.func, tainted)
    if isinstance(node, (ast.BinOp,)):
        return _expr_tainted(node.left, tainted) or _expr_tainted(node.right, tainted)
    if isinstance(node, ast.UnaryOp):
        return _expr_tainted(node.operand, tainted)
    if isinstance(node, ast.BoolOp):
        return any(_expr_tainted(v, tainted) for v in node.values)
    if isinstance(node, ast.Compare):
        return _expr_tainted(node.left, tainted) or any(
            _expr_tainted(c, tainted) for c in node.comparators
        )
    if isinstance(node, ast.Subscript):
        return _expr_tainted(node.value, tainted)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_expr_tainted(e, tainted) for e in node.elts)
    if isinstance(node, ast.IfExp):
        return any(_expr_tainted(n, tainted)
                   for n in (node.test, node.body, node.orelse))
    return False


def _taint(fn: ast.FunctionDef, params: Set[str]) -> Set[str]:
    """Parameters plus names assigned from tainted expressions (two
    fixpoint passes cover the straight-line chains that occur in
    practice)."""
    tainted = set(params)
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _expr_tainted(node.value, tainted):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
            elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
                if _expr_tainted(node.value, tainted) or node.target.id in tainted:
                    tainted.add(node.target.id)
    return tainted


def _is_none_check(test: ast.AST) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    )


def _mutable_globals(tree: ast.Module) -> Dict[str, int]:
    """Module-level names bound to mutable containers, with their line."""
    out: Dict[str, int] = {}
    mutable_ctors = {"list", "dict", "set", "collections.deque",
                     "collections.defaultdict", "deque", "defaultdict"}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        is_mut = isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                ast.DictComp, ast.SetComp))
        if isinstance(v, ast.Call):
            d = dotted_name(v.func)
            is_mut = is_mut or (d in mutable_ctors)
        if not is_mut:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = node.lineno
    return out


def _callees(fn: ast.FunctionDef,
             fns_by_name: Dict[str, List[ast.FunctionDef]],
             seen: Set[int]) -> List[ast.FunctionDef]:
    """Same-module functions (transitively) called by name from ``fn`` —
    the jit-reachable set for the impurity check."""
    out: List[ast.FunctionDef] = []
    stack = [fn]
    while stack:
        cur = stack.pop()
        for node in ast.walk(cur):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                for callee in fns_by_name.get(node.func.id, ()):
                    if id(callee) not in seen and callee is not fn:
                        seen.add(id(callee))
                        out.append(callee)
                        stack.append(callee)
    return out


def check(files: Sequence[SourceFile], config,
          full: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        tree = sf.tree
        assert isinstance(tree, ast.Module)
        sites = _wrap_sites(tree)
        if not sites:
            continue
        fns_by_name: Dict[str, List[ast.FunctionDef]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns_by_name.setdefault(node.name, []).append(node)
        mut_globals = _mutable_globals(tree)
        imports = _import_aliases(tree)
        reached: Set[int] = {id(s.fn) for s in sites}

        for site in sites:
            fn = site.fn
            params = _param_names(fn, site.static_idx, site.static_names)
            tainted = _taint(fn, params)
            local_defs = {
                n.name for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            }

            for node in ast.walk(fn):
                # DTL011: host control flow on a traced value
                if isinstance(node, (ast.If, ast.While)):
                    if (_expr_tainted(node.test, tainted)
                            and not _is_none_check(node.test)):
                        findings.append(Finding(
                            "DTL011", sf.path, node.lineno,
                            f"`{fn.name}` ({site.kind}-wrapped) branches "
                            f"host-side on a traced value — a retrace "
                            f"hazard or trace error; use lax.cond/select "
                            f"or mark the argument static",
                            anchor=f"{fn.name}:{type(node).__name__}",
                        ))
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                # DTL012: host syncs
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and _expr_tainted(node.func.value, tainted)):
                    findings.append(Finding(
                        "DTL012", sf.path, node.lineno,
                        f"`{fn.name}` calls .item() on a traced value — "
                        f"a device sync inside jit",
                        anchor=f"{fn.name}:item",
                    ))
                elif (d in _HOST_CASTS and node.args
                      and _expr_tainted(node.args[0], tainted)):
                    findings.append(Finding(
                        "DTL012", sf.path, node.lineno,
                        f"`{fn.name}` applies {d}() to a traced value — "
                        f"a trace error / host sync; keep it on-device "
                        f"(jnp cast) or mark the argument static",
                        anchor=f"{fn.name}:{d}",
                    ))
                elif (d in _HOST_ARRAY_FNS and node.args
                      and _expr_tainted(node.args[0], tainted)):
                    findings.append(Finding(
                        "DTL012", sf.path, node.lineno,
                        f"`{fn.name}` materializes a traced value with "
                        f"{d}() — a host sync inside jit (use jnp.asarray)",
                        anchor=f"{fn.name}:{d}",
                    ))
                # DTL014: mutable module-global closure
            for node in ast.walk(fn):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in mut_globals
                        and node.id not in tainted
                        and node.id not in local_defs):
                    findings.append(Finding(
                        "DTL014", sf.path, node.lineno,
                        f"`{fn.name}` closes over mutable module global "
                        f"`{node.id}` — cached traces freeze its trace-"
                        f"time contents and ignore later mutation",
                        anchor=f"{fn.name}:{node.id}",
                    ))

            # DTL013: impure calls, wrapped fn + same-module callees
            for body_fn in [fn] + _callees(fn, fns_by_name, reached):
                findings.extend(
                    _impure_calls(sf, body_fn, imports, origin=fn.name)
                )
    return findings


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local alias -> real module ('np' -> 'numpy')."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[(a.asname or a.name.split(".")[0])] = a.name
    return out


def _impure_calls(sf: SourceFile, fn: ast.FunctionDef,
                  imports: Dict[str, str], origin: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None:
            continue
        # normalize the leading alias to the real module name
        head, _, rest = d.partition(".")
        real = imports.get(head, head)
        full = f"{real}.{rest}" if rest else real

        def matches(prefixes) -> bool:
            return any(
                full.startswith(p) if p.endswith(".") else full == p
                for p in prefixes
            )

        if matches(_PURE_PREFIXES):
            continue
        if matches(_IMPURE_PREFIXES):
            where = (f"`{fn.name}`" if fn.name == origin
                     else f"`{fn.name}` (reached from jitted `{origin}`)")
            findings.append(Finding(
                "DTL013", sf.path, node.lineno,
                f"{where} calls {d}() inside a traced region — the value "
                f"is frozen at trace time (pass it in as an argument)",
                anchor=f"{fn.name}:{full}",
            ))
    return findings
