#!/usr/bin/env python
"""Bench trend gate: per-metric regression checks over the committed
``BENCH_r*.json`` history (ISSUE 19).

Every flagship measurement session appends to a committed history —
``BENCH_rNN.json`` files whose ``tail`` field holds the run's JSONL
records (one ``{"metric": ..., "value": ..., "unit": ...}`` object per
line). This tool turns that history into a NUMBER a PR can be gated on,
instead of a vibe:

- default: print the per-metric trend table (baseline, latest, delta,
  verdict) as JSON lines;
- ``--new FILE``: fold a fresh run's records (raw JSONL, or a
  BENCH_r-style JSON with a ``tail``) in as the latest point;
- ``--check``: exit nonzero iff any gated metric REGRESSED past its
  tolerance — the serve_smoke/chaos_soak lint pre-flight wires this in
  so a perf regression fails red before a correctness smoke even runs.

Direction is inferred per metric (latency/time/bytes/gap fall, MFU/
throughput/accept/hit rates rise); metrics whose direction is unknown
are reported but never gated. The baseline is the MEDIAN of the prior
points — a single historical outlier can neither mask nor fake a
regression. Pure stdlib, no jax: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys
from typing import Dict, List, Optional, Tuple

HISTORY_GLOB = "BENCH_r*.json"

# fractional tolerance before a delta counts as a regression; per-metric
# overrides first, the default for everything else. CPU-tier timings are
# noisy — the gate catches step changes, not jitter.
DEFAULT_TOLERANCE = 0.5
TOLERANCES: Dict[str, float] = {
    # MFU is a stable ratio: hold it tighter than wall-clock timings
    "train_mfu_dalle_depth12_dim1024_seq1280_1chip": 0.25,
}

# direction markers, matched against the metric name (and the unit as a
# fallback): the FIRST match wins, so put the more specific ones first
_LOWER_MARKERS = (
    "latency", "step_time", "_time", "gap", "_s_", "wait", "ttft",
    "bytes", "compiles", "recompiles", "mttr", "recovery",
)
_HIGHER_MARKERS = (
    "mfu", "per_sec", "per_s", "throughput", "tokens_sec", "accept",
    "hit_frac", "hit_rate", "images_per", "frac_of_roofline", "speedup",
)


def direction(metric: str, unit: Optional[str] = None) -> Optional[str]:
    """'lower' / 'higher' = which way is better; None = ungated."""
    name = metric.lower()
    for m in _LOWER_MARKERS:
        if m in name:
            return "lower"
    for m in _HIGHER_MARKERS:
        if m in name:
            return "higher"
    if unit in ("s", "ms", "us"):
        return "lower"
    return None


def parse_records(text: str) -> List[dict]:
    """Metric records from JSONL text: objects with a string ``metric``
    and a numeric ``value``; everything else is skipped (bench output
    interleaves assertions and notes with the records)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if (
            isinstance(obj, dict)
            and isinstance(obj.get("metric"), str)
            and isinstance(obj.get("value"), (int, float))
        ):
            out.append(obj)
    return out


def load_history_file(path: str) -> List[dict]:
    """Records from one history point — a BENCH_r-style JSON whose
    ``tail`` holds the JSONL, or a raw JSONL file."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and "tail" in obj:
        return parse_records(obj.get("tail") or "")
    return parse_records(text)


def collect_series(
    history_paths: List[str], new_path: Optional[str] = None
) -> Dict[str, List[Tuple[str, float, Optional[str]]]]:
    """metric -> ordered [(source, value, unit)] across history (path
    order = chronological; the glob sorts rNN lexically) plus the
    optional new point last. A metric repeated within one file keeps its
    last value (reruns within a session supersede)."""
    series: Dict[str, List[Tuple[str, float, Optional[str]]]] = {}
    for path in list(history_paths) + ([new_path] if new_path else []):
        per_file: Dict[str, Tuple[float, Optional[str]]] = {}
        for rec in load_history_file(path):
            per_file[rec["metric"]] = (
                float(rec["value"]), rec.get("unit")
            )
        name = os.path.basename(path)
        for metric, (value, unit) in sorted(per_file.items()):
            series.setdefault(metric, []).append((name, value, unit))
    return series


def evaluate(
    series: Dict[str, List[Tuple[str, float, Optional[str]]]],
    tolerance: float = DEFAULT_TOLERANCE,
) -> List[dict]:
    """One verdict row per metric. Gated metrics with >=2 points compare
    the LATEST value against the median of the prior points; single-point
    or direction-unknown metrics report ``ungated``."""
    rows = []
    for metric in sorted(series):
        points = series[metric]
        unit = points[-1][2]
        d = direction(metric, unit)
        latest_src, latest, _ = points[-1]
        row = {
            "metric": metric,
            "n_points": len(points),
            "latest": latest,
            "latest_source": latest_src,
            "unit": unit,
            "direction": d,
        }
        if d is None or len(points) < 2:
            row["status"] = "ungated"
            rows.append(row)
            continue
        baseline = statistics.median(v for _, v, _ in points[:-1])
        tol = TOLERANCES.get(metric, tolerance)
        row["baseline"] = baseline
        row["tolerance"] = tol
        if baseline == 0:
            row["status"] = "ungated"
            rows.append(row)
            continue
        delta = (latest - baseline) / abs(baseline)
        row["delta_frac"] = delta
        regressed = (
            delta > tol if d == "lower" else delta < -tol
        )
        row["status"] = "regressed" if regressed else "ok"
        rows.append(row)
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--history-glob", default=HISTORY_GLOB,
        help="committed history files, sorted = chronological",
    )
    ap.add_argument(
        "--root", default=None,
        help="directory the history glob is relative to (default: the "
             "repo root this tool lives in)",
    )
    ap.add_argument(
        "--new", default=None, metavar="FILE",
        help="fold a fresh run's records (JSONL or BENCH_r-style JSON) "
             "in as the latest point",
    )
    ap.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help="default fractional regression tolerance",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="exit nonzero iff any gated metric regressed",
    )
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    paths = sorted(glob.glob(os.path.join(root, args.history_glob)))
    if not paths and not args.new:
        print(json.dumps({"error": "no history matched", "root": root}))
        return 2

    series = collect_series(paths, args.new)
    rows = evaluate(series, args.tolerance)
    for row in rows:
        print(json.dumps(row))
    regressed = [r for r in rows if r["status"] == "regressed"]
    summary = {
        "summary": "bench_trend",
        "history_points": len(paths) + (1 if args.new else 0),
        "metrics": len(rows),
        "gated": sum(r["status"] != "ungated" for r in rows),
        "regressed": len(regressed),
    }
    print(json.dumps(summary))
    if args.check and regressed:
        for r in regressed:
            print(
                f"REGRESSION {r['metric']}: latest {r['latest']:.6g} vs "
                f"baseline {r['baseline']:.6g} "
                f"(delta {r['delta_frac']:+.1%}, tol "
                f"{r['tolerance']:.0%}, {r['direction']} is better)",
                file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
