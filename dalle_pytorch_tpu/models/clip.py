"""CLIP dual-encoder for generation reranking, TPU-native.

Capability parity with the reference's ``CLIP`` (dalle_pytorch.py:229-305):
text transformer + ViT-style patch image transformer (both non-causal, no
rotary), masked-mean / mean pooling, bias-free latent projections, L2
normalization and a learned temperature; training mode is the symmetric
InfoNCE cross-entropy over the batch. Patchify is a reshape/transpose (XLA
fuses it into the first matmul), not a conv.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from .transformer import Transformer

Dtype = Any


def masked_mean(t: jnp.ndarray, mask: jnp.ndarray, axis: int = 1) -> jnp.ndarray:
    """Mean over ``axis`` counting only True positions (reference
    dalle_pytorch.py:31-33)."""
    t = jnp.where(mask[..., None], t, 0.0)
    return t.sum(axis=axis) / mask.sum(axis=axis)[..., None]


class CLIP(nn.Module):
    dim_text: int = 512
    dim_image: int = 512
    dim_latent: int = 512
    num_text_tokens: int = 10000
    text_enc_depth: int = 6
    text_seq_len: int = 256
    text_heads: int = 8
    text_dim_head: int = 64
    num_visual_tokens: int = 512
    visual_enc_depth: int = 6
    visual_heads: int = 8
    visual_dim_head: int = 64
    visual_image_size: int = 256
    visual_patch_size: int = 32
    channels: int = 3
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @property
    def num_patches(self) -> int:
        assert self.visual_image_size % self.visual_patch_size == 0, (
            "Image dimensions must be divisible by the patch size."
        )
        return (self.visual_image_size // self.visual_patch_size) ** 2

    def setup(self):
        self.text_emb = nn.Embed(self.num_text_tokens, self.dim_text, param_dtype=self.param_dtype)
        self.text_pos_emb = nn.Embed(self.text_seq_len, self.dim_text, param_dtype=self.param_dtype)
        self.text_transformer = Transformer(
            dim=self.dim_text,
            depth=self.text_enc_depth,
            seq_len=self.text_seq_len,
            causal=False,
            heads=self.text_heads,
            # the reference's CLIP transformers always use dim_head=64 (the
            # Transformer default; dalle_pytorch.py:250,260 pass heads only)
            dim_head=self.text_dim_head,
            rotary_emb=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        self.to_text_latent = nn.Dense(
            self.dim_latent, use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype
        )

        self.to_visual_embedding = nn.Dense(
            self.dim_image, dtype=self.dtype, param_dtype=self.param_dtype
        )
        self.visual_pos_emb = nn.Embed(
            self.num_patches, self.dim_image, param_dtype=self.param_dtype
        )
        self.visual_transformer = Transformer(
            dim=self.dim_image,
            depth=self.visual_enc_depth,
            seq_len=self.num_patches,
            causal=False,
            heads=self.visual_heads,
            dim_head=self.visual_dim_head,
            rotary_emb=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        self.to_visual_latent = nn.Dense(
            self.dim_latent, use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype
        )

        self.temperature = self.param(
            "temperature", nn.initializers.ones, (), self.param_dtype
        )

    def patchify(self, image: jnp.ndarray) -> jnp.ndarray:
        """(b, h, w, c) NHWC -> (b, num_patches, p*p*c)."""
        p = self.visual_patch_size
        b, h, w, c = image.shape
        image = image.reshape(b, h // p, p, w // p, p, c)
        return image.transpose(0, 1, 3, 2, 4, 5).reshape(b, (h // p) * (w // p), p * p * c)

    def __call__(
        self,
        text: jnp.ndarray,
        image: jnp.ndarray,
        text_mask: Optional[jnp.ndarray] = None,
        return_loss: bool = False,
        deterministic: bool = True,
    ):
        """text: (b, text_seq_len) int ids; image: (b, h, w, c) pixels.
        Returns per-pair similarity (b,) or the symmetric CE loss."""
        b = text.shape[0]
        text_tokens = self.text_emb(text) + self.text_pos_emb(jnp.arange(text.shape[1]))[None]

        image_patches = self.patchify(image.astype(self.dtype))
        image_tokens = self.to_visual_embedding(image_patches)
        image_tokens = image_tokens + self.visual_pos_emb(jnp.arange(image_tokens.shape[1]))[None]

        enc_text = self.text_transformer(
            text_tokens.astype(self.dtype), mask=text_mask, deterministic=deterministic
        )
        enc_image = self.visual_transformer(image_tokens, deterministic=deterministic)

        if text_mask is not None:
            text_latents = masked_mean(enc_text, text_mask, axis=1)
        else:
            text_latents = enc_text.mean(axis=1)
        image_latents = enc_image.mean(axis=1)

        text_latents = self.to_text_latent(text_latents).astype(jnp.float32)
        image_latents = self.to_visual_latent(image_latents).astype(jnp.float32)

        text_latents = text_latents / jnp.linalg.norm(text_latents, axis=-1, keepdims=True)
        image_latents = image_latents / jnp.linalg.norm(image_latents, axis=-1, keepdims=True)

        temp = jnp.exp(self.temperature)

        if not return_loss:
            return jnp.einsum("nd,nd->n", text_latents, image_latents) * temp

        sim = jnp.einsum("id,jd->ij", text_latents, image_latents) * temp
        labels = jnp.arange(b)
        logp_t = jax.nn.log_softmax(sim, axis=-1)
        logp_i = jax.nn.log_softmax(sim.T, axis=-1)
        loss_t = -jnp.take_along_axis(logp_t, labels[:, None], axis=-1).mean()
        loss_i = -jnp.take_along_axis(logp_i, labels[:, None], axis=-1).mean()
        return (loss_t + loss_i) / 2
