"""Transformer composition, TPU-native.

Mirrors the reference's ``Transformer`` capability surface
(transformer.py:130-227): per-layer attention types cycled from
``attn_types`` (full / axial_row / axial_col / conv_like / sparse / mlp),
LayerScale(PreNorm(...)) stacking with depth-dependent init, optional token
shift, optional reversible or rematerialized execution, and the DALL-E 3-part
rotary table — but built as a functional JAX stack: static shapes throughout,
one compiled graph, explicit PRNG keys, and a decode mode that threads KV /
shift caches for O(1)-per-token sampling.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

import functools

from ..ops.attention import PatternAttention
from ..ops.flash_attention import StaticTable
from ..ops.layers import (
    FeedForward,
    GMLPBlock,
    LayerScale,
    PreNorm,
    PreShiftToken,
)
from ..ops.moe import MoEFeedForward
from ..ops.reversible import reversible_forward_only, reversible_sequence
from ..ops.rotary import angles, dalle_rotary_table, lang_freqs

Dtype = Any

ATTENTION_TYPES = ("full", "axial_row", "axial_col", "conv_like", "sparse", "mlp")


def cast_tuple(val, depth: int = 1) -> tuple:
    if isinstance(val, list):
        val = tuple(val)
    return val if isinstance(val, tuple) else (val,) * depth


@functools.lru_cache(maxsize=None)
def _interned_rotary(data: bytes, shape: tuple) -> StaticTable:
    """Content-interned StaticTable: setup() runs on every init/apply, and
    the fused attention kernel hashes tables by id — interning keeps the
    id stable across traces so nothing retraces or recompiles."""
    return StaticTable(np.frombuffer(data, dtype=np.float32).reshape(shape))


class Transformer(nn.Module):
    """Depth-wise composition of attention + GEGLU feed-forward blocks.

    ``seq_len`` is the model sequence length (text_seq + image_seq for DALL-E;
    the encoder length for CLIP). When ``image_fmap_size`` is set, the
    internal attention pattern length is seq_len + 1 (<bos> included), exactly
    like the reference's internal padding (attention.py:121-124).

    Execution modes: sequential (default), ``reversible=True`` (O(1)
    activation memory via ops/reversible.py), or ``remat=True``
    (jax.checkpoint per block — recompute in backward, standard pytree
    activations).
    """

    dim: int
    depth: int
    seq_len: int
    reversible: bool = False
    causal: bool = True
    heads: int = 8
    dim_head: int = 64
    ff_mult: float = 4
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    attn_types: Optional[Tuple[str, ...]] = None
    image_fmap_size: Optional[int] = None
    stable: bool = False
    shift_tokens: bool = False
    # extra token-shift ring rows — speculative-decode rollback slack
    # (ops/layers.py:PreShiftToken.pad); 0 for every non-speculative model
    shift_pad: int = 0
    rotary_emb: bool = True
    remat: bool = False
    sparse_layout_seed: int = 0
    use_flash: bool = True
    sp_axis: Optional[str] = None
    pp_axis: Optional[str] = None
    pp_microbatches: int = 4
    ff_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    quant: bool = False
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    def _attn_seq_len(self) -> int:
        return self.seq_len + 1 if self.image_fmap_size is not None else self.seq_len

    def rotary_table(self) -> Optional[np.ndarray]:
        if not self.rotary_emb:
            return None
        if self.image_fmap_size is not None:
            img_seq_len = self.image_fmap_size**2
            text_len = self.seq_len - img_seq_len + 1
            table = dalle_rotary_table(self.dim_head, text_len, self.image_fmap_size)
        else:
            # plain 1-D rotary fallback (no image grid present)
            table = angles(
                np.arange(self.seq_len), lang_freqs(self.dim_head // 2)
            ).astype(np.float32)
        # zero-pad the angle table to the full head dim: zero angle = identity
        # rotation for the channels the reference leaves untouched, and a
        # full-width table lets apply_rotary_emb stay purely elementwise
        # (measured ~6 ms/step of XLA layout copies at the flagship config)
        pad = self.dim_head - table.shape[-1]
        if pad > 0:
            table = np.pad(table, ((0, 0), (0, pad)))
        return table

    def setup(self):
        attn_types = cast_tuple(self.attn_types or ("full",))
        for t in attn_types:
            if t not in ATTENTION_TYPES:
                raise ValueError(f'attention type "{t}" is not valid')
        if self.rotary_emb and "mlp" in attn_types:
            raise ValueError("gMLP layers cannot be combined with rotary embeddings")
        if self.sp_axis is not None and "mlp" in attn_types:
            raise ValueError(
                "gMLP spatial gating mixes the whole sequence locally and "
                "cannot run sequence-parallel; drop 'mlp' from attn_types "
                "or disable sp"
            )
        if self.ff_experts > 0 and self.moe_every <= 0:
            raise ValueError(
                f"moe_every must be >= 1 (every n-th FF becomes an expert "
                f"layer); got {self.moe_every}"
            )
        attn_blocks, ff_blocks, kinds = [], [], []
        for ind in range(self.depth):
            attn_type = attn_types[ind % len(attn_types)]
            if attn_type == "mlp":
                attn = GMLPBlock(
                    dim=self.dim,
                    dim_ff=self.dim * 4,
                    seq_len=self.seq_len,
                    causal=self.causal,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                )
            else:
                attn = PatternAttention(
                    dim=self.dim,
                    seq_len=self._attn_seq_len(),
                    attn_type=attn_type,
                    causal=self.causal,
                    heads=self.heads,
                    dim_head=self.dim_head,
                    dropout=self.attn_dropout,
                    stable=self.stable,
                    image_fmap_size=self.image_fmap_size,
                    layout_seed=self.sparse_layout_seed + ind,
                    use_flash=self.use_flash,
                    sp_axis=self.sp_axis,
                    quant=self.quant,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                )
            if self.ff_experts > 0 and ind % self.moe_every == self.moe_every - 1:
                # GShard-style: every moe_every-th FF becomes a Switch-routed
                # expert layer (ops/moe.py); experts shard over the ep axis
                ff = MoEFeedForward(
                    dim=self.dim,
                    num_experts=self.ff_experts,
                    mult=self.ff_mult,
                    capacity_factor=self.moe_capacity_factor,
                    dropout=self.ff_dropout,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                )
            else:
                ff = FeedForward(
                    dim=self.dim,
                    mult=self.ff_mult,
                    dropout=self.ff_dropout,
                    quant=self.quant,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                )

            if self.shift_tokens:
                assert self.image_fmap_size is not None
                attn = PreShiftToken(
                    fn=attn,
                    image_size=self.image_fmap_size,
                    seq_len=self.seq_len,
                    pass_decode=True,
                    pad=self.shift_pad,
                )
                ff = PreShiftToken(
                    fn=ff, image_size=self.image_fmap_size,
                    seq_len=self.seq_len, pad=self.shift_pad,
                )

            attn_blocks.append(
                LayerScale(
                    dim=self.dim,
                    depth=ind + 1,
                    fn=PreNorm(dim=self.dim, fn=attn, param_dtype=self.param_dtype),
                    param_dtype=self.param_dtype,
                    name=f"attn_{ind}",
                )
            )
            ff_blocks.append(
                LayerScale(
                    dim=self.dim,
                    depth=ind + 1,
                    fn=PreNorm(dim=self.dim, fn=ff, param_dtype=self.param_dtype),
                    param_dtype=self.param_dtype,
                    name=f"ff_{ind}",
                )
            )
            kinds.append(attn_type)

        self.attn_blocks = attn_blocks
        self.ff_blocks = ff_blocks
        self.layer_kinds = tuple(kinds)

    # ------------------------------------------------------------------ call

    def _block_kwargs(self, ind: int, mask, rot, deterministic, decode,
                      block_len=None, block_start=None):
        """(attn kwargs, ff kwargs) for layer ``ind`` in module-call form."""
        kind = self.layer_kinds[ind]
        akw: dict = dict(deterministic=deterministic, decode=decode)
        if kind != "mlp":
            akw.update(mask=mask, rotary_pos_emb=rot)
            if block_len is not None:
                akw["block_len"] = block_len
            if block_start is not None:
                akw["block_start"] = block_start
        fkw: dict = dict(deterministic=deterministic)
        if self.shift_tokens:
            fkw.update(decode=decode)
            if block_len is not None:
                # the FF-side PreShiftToken consumes block_len for its own
                # ragged ring advance (it never forwards it to the FF)
                fkw["block_len"] = block_len
            if block_start is not None:
                fkw["block_start"] = block_start
        return akw, fkw

    def __call__(
        self,
        x: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
        decode: bool = False,
        block_len: Optional[jnp.ndarray] = None,
        block_start: Optional[jnp.ndarray] = None,
        depth_limit: Optional[int] = None,
    ) -> jnp.ndarray:
        rot_np = self.rotary_table()
        # a content-interned StaticTable, not a traced array: the attention
        # layer materializes it for the unfused/decode paths and consumes it
        # statically in the fused kernel — one source of truth for both
        rot = (
            _interned_rotary(rot_np.astype(np.float32).tobytes(), rot_np.shape)
            if rot_np is not None else None
        )

        if (
            self.pp_axis is not None
            and not decode
            and not self.is_initializing()
        ):
            from ..parallel.context import axis_extent

            if axis_extent(self.pp_axis) > 1:
                return self._pp_forward(x, mask, rot, deterministic)

        sequential = (
            self.is_initializing()
            or decode
            or (not self.reversible and not self.remat)
        )
        # depth_limit (static): run only the first L layers — the
        # early-exit self-draft pass of speculative decoding
        # (serving/engine.py). Decode-mode only: training/prefill always
        # runs the full stack. None (every non-speculative caller) is the
        # full depth.
        depth_eff = (
            self.depth if depth_limit is None
            else min(max(int(depth_limit), 1), self.depth)
        )

        if sequential and not self.reversible:
            for ind in range(depth_eff):
                akw, fkw = self._block_kwargs(
                    ind, mask, rot, deterministic, decode, block_len,
                    block_start,
                )
                x = x + self.attn_blocks[ind](x, **akw)
                x = x + self.ff_blocks[ind](x, **fkw)
            return x

        if self.reversible and (self.is_initializing() or decode):
            # reversible wiring, run directly (no custom VJP needed)
            x1, x2 = x, x
            for ind in range(depth_eff):
                akw, fkw = self._block_kwargs(
                    ind, mask, rot, deterministic, decode, block_len,
                    block_start,
                )
                x1 = x1 + self.attn_blocks[ind](x2, **akw)
                x2 = x2 + self.ff_blocks[ind](x1, **fkw)
            return (x1 + x2) / 2

        # pure-function paths: remat or reversible training. Block closures
        # return (delta, aux); the Switch load-balance loss rides the aux
        # channel (re-sown below) so MoE composes with O(1)-memory execution.
        fns, params, kwargs = self._pure_blocks(mask, rot, deterministic)

        if self.remat and not self.reversible:
            aux = jnp.zeros((), jnp.float32)
            for (f, g), (pf, pg), (kwf, kwg) in zip(fns, params, kwargs):
                d, a = jax.checkpoint(f)(pf, x, kwf)
                x = x + d
                dg, ag = jax.checkpoint(g)(pg, x, kwg)
                x = x + dg
                aux = aux + a + ag
            if self.ff_experts > 0:
                self.sow("moe_aux", "load_balance", aux)
            return x

        out, aux = reversible_sequence(
            tuple(fns), params, jnp.concatenate((x, x), -1), kwargs
        )
        if self.ff_experts > 0:
            self.sow("moe_aux", "load_balance", aux)
        y1, y2 = jnp.split(out, 2, axis=-1)
        return (y1 + y2) / 2

    def _pp_forward(self, x, mask, rot, deterministic):
        """GPipe pipeline execution over the ``pp_axis`` mesh axis
        (parallel/pipeline.py): per-layer params are stacked and staged, the
        microbatch schedule runs as one shard_map. Requires homogeneous
        layers (uniform attn_types; 'mlp' has different params and 'sparse'
        a different mask per layer) and no reversible mode. Key-padding
        masks ride the microbatch schedule alongside the activations;
        dropout derives per-(layer, microbatch) keys with fold_in inside the
        schedule (bitwise-deterministic given the base key, though the
        draw pattern differs from the no-pp run, which draws one mask over
        the whole batch). Composes with dp/fsdp/tp — only the pp axis is
        manual in the shard_map; tensor-parallel layers shard via GSPMD
        inside the stage (sp cannot nest: ring attention opens its own
        shard_map)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.context import active_mesh, axis_extent, batch_axes
        from ..parallel.pipeline import gpipe, stack_layer_params

        kinds = set(self.layer_kinds)
        if len(kinds) != 1 or kinds & {"mlp", "sparse"}:
            raise ValueError(
                f"pipeline parallelism needs one uniform attention type "
                f"(not mlp/sparse, whose layers are heterogeneous); got "
                f"{self.attn_types}"
            )
        if self.ff_experts > 0 and self.moe_every != 1:
            raise ValueError(
                "pipeline parallelism requires homogeneous stages: with "
                "MoE feed-forwards every layer must be MoE (set "
                f"moe_every=1; got moe_every={self.moe_every}, whose "
                "dense/MoE alternation gives stages different param "
                "structures)"
            )
        if self.reversible:
            raise ValueError("pipeline parallelism excludes reversible mode")
        if axis_extent("sp") > 1:
            raise ValueError(
                "pp composes with dp/fsdp/tp but not sp: sequence-parallel "
                "attention opens its own shard_map, which cannot nest "
                "inside the pipeline stage"
            )

        mesh = active_mesh()
        pp = int(mesh.shape[self.pp_axis])
        assert self.depth % pp == 0, (
            f"depth {self.depth} not divisible by pp={pp}"
        )
        # Only the pp axis is manual; dp/fsdp/tp stay auto (GSPMD) inside
        # the stage body, so the microbatch split below sees the GLOBAL
        # batch and tensor-parallel layers shard transparently. The split
        # must still divide evenly across the data-parallel extent.
        dp_total = int(
            np.prod([mesh.shape[a] for a in (batch_axes(mesh) or ())])
        )
        local_b = x.shape[0] // dp_total
        # largest microbatch count that divides the per-shard batch
        n_micro = max(
            m
            for m in range(1, min(self.pp_microbatches, local_b) + 1)
            if local_b % m == 0
        )
        if n_micro < min(self.pp_microbatches, pp):
            import warnings

            warnings.warn(
                f"pipeline microbatches reduced to {n_micro} (requested "
                f"{self.pp_microbatches}; per-shard batch {local_b} has no "
                f"larger divisor) — the GPipe bubble grows accordingly; "
                f"pick a batch size divisible by dp*fsdp*microbatches"
            )

        # with_rng=False: the pipeline derives its own per-(layer, micro)
        # dropout keys below instead of _pure_blocks' per-layer draws
        fns, params, _ = self._pure_blocks(None, rot, deterministic, with_rng=False)
        attn_f, ff_f = fns[0]
        stacked = stack_layer_params(
            [{"attn": pa, "ff": pf} for pa, pf in params]
        )
        # (depth, ...) -> (pp, depth // pp, ...) so dim 0 shards over pp
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape(pp, self.depth // pp, *l.shape[1:]), stacked
        )

        needs_rng = (
            not deterministic and (self.attn_dropout > 0 or self.ff_dropout > 0)
        )
        base_key = self.make_rng("dropout") if needs_rng else None
        rot_kw = {"rot": rot} if rot is not None else {}

        def layer_fn(p, t, side, layer_idx, micro_idx, key):
            akw, fkw = dict(rot_kw), {}
            if side:
                akw["mask"] = side["mask"]
            if key is not None:
                # one deterministic draw per (layer, microbatch, attn/ff)
                lk = jax.random.fold_in(
                    jax.random.fold_in(key, layer_idx), micro_idx
                )
                akw["rng"] = jax.random.fold_in(lk, 0)
                fkw["rng"] = jax.random.fold_in(lk, 1)
            d, a1 = attn_f(p["attn"], t, akw)
            t = t + d
            d, a2 = ff_f(p["ff"], t, fkw)
            return t + d, a1 + a2

        if self.remat:
            # honor --remat inside the pipeline: recompute each layer's
            # activations in backward instead of storing them across the
            # n_micro + pp - 1 scan ticks
            layer_fn = jax.checkpoint(layer_fn)

        p_specs = jax.tree_util.tree_map(lambda _: P(self.pp_axis), stacked)
        x_spec = P()  # batch stays auto-sharded over dp/fsdp by GSPMD
        side = {"mask": mask} if mask is not None else None
        side_specs = {"mask": P()} if mask is not None else None
        key_spec = None if base_key is None else P()

        def body(p, t, s, k):
            return gpipe(
                lambda pl, tl_, sl, li, mi: layer_fn(pl, tl_, sl, li, mi, k),
                p, t,
                axis_name=self.pp_axis, n_stages=pp, n_micro=n_micro,
                side=s,
            )

        from ..ops.jax_compat import shard_map

        out, aux = shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, x_spec, side_specs, key_spec),
            out_specs=(x_spec, P()),
            axis_names=frozenset({self.pp_axis}),
            check_vma=False,
        )(stacked, x, side, base_key)
        if self.ff_experts > 0:
            # per-microbatch Switch aux averaged over microbatches — a
            # consistent estimator of the sequential path's full-batch aux
            # (equal when routing statistics match across microbatches)
            self.sow("moe_aux", "load_balance", aux / n_micro)
        return out

    def _pure_blocks(self, mask, rot, deterministic, with_rng=True):
        """Unbound-apply closures + param subtrees + traced-array kwargs for
        the custom-VJP / remat execution paths. ``with_rng=False`` skips the
        per-layer dropout-key draws (the pp path folds its own keys)."""
        variables = self.variables["params"]

        needs_rng = (
            with_rng
            and not deterministic
            and (self.attn_dropout > 0 or self.ff_dropout > 0)
        )

        fns, params, kwargs = [], [], []
        for ind in range(self.depth):
            kind = self.layer_kinds[ind]
            attn_mod = self.attn_blocks[ind].clone(parent=None)
            ff_mod = self.ff_blocks[ind].clone(parent=None)

            def make_fn(mod, is_attn, kind=kind):
                static_kwargs = dict(deterministic=deterministic)

                def fn(p, t, kw):
                    call_kwargs = dict(static_kwargs)
                    if is_attn and kind != "mlp":
                        call_kwargs["mask"] = kw.get("mask")
                        call_kwargs["rotary_pos_emb"] = kw.get("rot")
                    rngs = {"dropout": kw["rng"]} if "rng" in kw else None
                    y, mut = mod.apply(
                        {"params": p}, t, rngs=rngs, mutable=["moe_aux"],
                        **call_kwargs,
                    )
                    aux = sum(
                        jax.tree_util.tree_leaves(mut.get("moe_aux", {})),
                        jnp.zeros((), jnp.float32),
                    )
                    return y, aux

                return fn

            akw: dict = {}
            if kind != "mlp":
                if mask is not None:
                    akw["mask"] = mask
                if rot is not None:
                    akw["rot"] = rot
            fkw: dict = {}
            if needs_rng:
                akw["rng"] = self.make_rng("dropout")
                fkw["rng"] = self.make_rng("dropout")

            fns.append((make_fn(attn_mod, True), make_fn(ff_mod, False)))
            params.append((variables[f"attn_{ind}"], variables[f"ff_{ind}"]))
            kwargs.append((akw, fkw))
        return fns, params, kwargs
