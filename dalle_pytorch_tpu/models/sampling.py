"""Autoregressive sampling for DALL-E, TPU-native.

The reference samples by re-running the full forward pass over the whole
prefix for every generated token (dalle_pytorch.py:481-486) — O(L^2) attention
work per token. Here generation is ONE parallel ``DALLE.prefill_step`` pass
over the text prompt (filling every decode cache with MXU-shaped matmuls)
followed by a single ``lax.scan`` over the KV-cached ``DALLE.decode_step``
for the image positions — each step one (1 x L) attention per layer, the
whole sequence one XLA program. Priming beyond the text prompt is
teacher-forced inside the scan via ``known_len``. Randomness flows through
explicit PRNG keys; top-k fractional-threshold filtering, temperature,
image-token priming (reference dalle_pytorch.py:470-479) and CLIP reranking
(dalle_pytorch.py:503-505) all match the reference semantics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .dalle import DALLE, top_k_filter


def init_decode_cache(dalle: DALLE, params, batch_size: int):
    """Materialize the transformer's KV/shift caches for a batch."""
    token = jnp.zeros((batch_size,), dtype=jnp.int32)
    _, mutated = dalle.apply(
        {"params": params},
        token,
        jnp.array(0, jnp.int32),
        method=DALLE.decode_step,
        mutable=["cache"],
    )
    return mutated["cache"]


@partial(jax.jit, static_argnums=(0, 5, 8, 9))
def decode_tokens(
    dalle: DALLE,
    params,
    tokens: jnp.ndarray,
    known_len: int,
    key: jax.Array,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    mask: Optional[jnp.ndarray] = None,
    num_steps: Optional[int] = None,
    prefill_len: int = 0,
):
    """Run the decode scan over the internal token buffer.

    tokens: (b, n_internal) int32 — position 0 is <bos>; the first
    ``known_len`` positions are prompt (teacher-forced), the rest are filled by
    sampling. ``known_len`` is traced, so varying prompt/prime lengths reuse
    one compilation. Text positions hold remapped text ids, image positions
    hold un-offset image token ids. Scans ``num_steps`` (default
    n_internal - 1) input positions and returns the completed buffer.

    ``prefill_len`` (static): process that many leading positions in one
    parallel ``DALLE.prefill_step`` pass instead of sequential scan steps —
    callers must guarantee known_len >= prefill_len and prefill_len <=
    text_len_internal (image generation prefills the whole text prompt,
    cutting the sequential steps from n_internal-1 to image_seq_len).
    Note: prefill consumes ONE PRNG split for the whole block where the
    sequential path consumed one per position, so sampled tokens for a given
    key differ between prefill_len settings (logits and caches are
    bit-identical; only the key stream shifts).
    """
    b, n_internal = tokens.shape
    steps = n_internal - 1 if num_steps is None else num_steps
    text_len_internal = dalle.text_len_internal
    ext = dalle.num_text_tokens_ext

    cache = init_decode_cache(dalle, params, b)

    # after a full-text-prompt prefill, every sampled position is an image
    # position whose text-vocab logits are masked (NEG_INF fill) — slicing to
    # the live image segment samples the same distribution (masked entries
    # rank below every real logit, so the full-vocab k gives the same
    # threshold) and shrinks the per-token top-k sort from total_tokens to
    # num_image_tokens wide; with the reference's fractional k it often
    # disappears entirely (k >= image vocab => no filtering). Like prefill,
    # this shifts the PRNG consumption (categorical draws over a narrower
    # array), so sampled tokens for a given key differ from the full-vocab
    # path while remaining distributionally identical.
    image_only = prefill_len == text_len_internal
    k_full = max(int((1 - filter_thres) * dalle.total_tokens), 1)

    def apply_sample(tokens, key, logits, i):
        """Sample the token at position i+1 from consumed-position-i logits
        (teacher-forced while i+1 < known_len)."""
        key, sub = jax.random.split(key)
        filtered = (
            top_k_filter(logits[:, ext:], k=k_full)
            if image_only
            else top_k_filter(logits, thres=filter_thres)
        )
        sample = jax.random.categorical(sub, filtered / temperature, axis=-1)
        nxt = i + 1
        if not image_only:
            sample = jnp.where(nxt >= text_len_internal, sample - ext, sample)
        prev = jax.lax.dynamic_slice_in_dim(tokens, nxt, 1, axis=1)[:, 0]
        new_val = jnp.where(nxt < known_len, prev, sample).astype(tokens.dtype)
        tokens = jax.lax.dynamic_update_slice(tokens, new_val[:, None], (0, nxt))
        return tokens, key

    start = 0
    if prefill_len > 1:
        logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            tokens[:, :prefill_len],
            mask,
            method=DALLE.prefill_step,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        tokens, key = apply_sample(tokens, key, logits, prefill_len - 1)
        start = prefill_len

    def step(carry, i):
        cache, tokens, key = carry
        tok_in = jax.lax.dynamic_slice_in_dim(tokens, i, 1, axis=1)[:, 0]
        logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            tok_in,
            i,
            mask,
            method=DALLE.decode_step,
            mutable=["cache"],
        )
        tokens, key = apply_sample(tokens, key, logits, i)
        return (mutated["cache"], tokens, key), None

    # unrolling amortizes per-step loop overhead in the bandwidth-bound
    # decode (measured ~2% p50 latency on v5e at unroll=4)
    (_, tokens, _), _ = jax.lax.scan(
        step, (cache, tokens, key), jnp.arange(start, steps, dtype=jnp.int32),
        unroll=4,
    )
    return tokens


def generate_image_tokens(
    dalle: DALLE,
    params,
    text: jnp.ndarray,
    key: jax.Array,
    *,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    prime_tokens: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """text: (b, text_seq_len) raw ids -> sampled image token ids
    (b, image_seq_len)."""
    b = text.shape[0]
    text = text[:, : dalle.text_seq_len].astype(jnp.int32)
    # remap_text touches no params, so the unbound-module call is safe
    internal_text = dalle.remap_text(text)

    n_internal = dalle.text_len_internal + dalle.image_seq_len
    tokens = jnp.zeros((b, n_internal), dtype=jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, internal_text, (0, 0))

    known_len = dalle.text_len_internal
    if prime_tokens is not None:
        assert prime_tokens.shape[1] < dalle.image_seq_len, (
            "number of priming image tokens must be < image_seq_len"
        )
        tokens = jax.lax.dynamic_update_slice(
            tokens, prime_tokens.astype(jnp.int32), (0, dalle.text_len_internal)
        )
        known_len += int(prime_tokens.shape[1])

    tokens = decode_tokens(
        dalle, params, tokens, known_len, key,
        filter_thres=filter_thres, temperature=temperature, mask=mask,
        prefill_len=dalle.text_len_internal,
    )
    return tokens[:, dalle.text_len_internal :]


def generate_images(
    dalle: DALLE,
    params,
    vae,
    vae_variables,
    text: jnp.ndarray,
    key: jax.Array,
    *,
    clip=None,
    clip_variables=None,
    mask: Optional[jnp.ndarray] = None,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    img: Optional[jnp.ndarray] = None,
    num_init_img_tokens: Optional[int] = None,
):
    """Full text -> pixels pipeline (reference generate_images,
    dalle_pytorch.py:451-507): optional image priming with
    ``int(0.4375 * image_seq_len)`` tokens, scan-decode, VAE decode, optional
    CLIP rerank. ``vae`` / ``clip`` are flax modules sharing the reference's
    duck-type (get_codebook_indices / decode; __call__ similarity)."""
    text = text[:, : dalle.text_seq_len]  # rerank sees the same truncated text
    prime = None
    if img is not None:
        indices = vae.apply(vae_variables, img, method=type(vae).get_codebook_indices)
        n_prime = (
            int(0.4375 * dalle.image_seq_len)
            if num_init_img_tokens is None
            else num_init_img_tokens
        )
        prime = indices[:, :n_prime]

    img_seq = generate_image_tokens(
        dalle, params, text, key,
        filter_thres=filter_thres, temperature=temperature,
        prime_tokens=prime, mask=mask,
    )
    images = vae.apply(vae_variables, img_seq, method=type(vae).decode)

    if clip is not None:
        scores = clip.apply(clip_variables, text, images)
        return images, scores
    return images


def generate_texts(
    dalle: DALLE,
    params,
    key: jax.Array,
    prompt_tokens: Optional[jnp.ndarray] = None,
    *,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    tokenizer=None,
):
    """Text-only completion (reference generate_texts,
    dalle_pytorch.py:403-449): start from <bos> (plus an optional encoded
    prompt) and sample out to text_seq_len tokens. Returns (tokens, texts) —
    texts only when a tokenizer with pad-aware decode is supplied."""
    if prompt_tokens is None:
        prompt_tokens = jnp.zeros((1, 1), dtype=jnp.int32)
    b, p = prompt_tokens.shape

    tokens = jnp.zeros((b, dalle.text_len_internal + dalle.image_seq_len), jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, prompt_tokens.astype(jnp.int32), (0, 0))

    tokens = decode_tokens(
        dalle, params, tokens, p, key,
        filter_thres=filter_thres, temperature=temperature,
        num_steps=dalle.text_seq_len - 1,
    )
    text_tokens = tokens[:, : dalle.text_seq_len]

    if tokenizer is None:
        return text_tokens, None
    pad_tokens = set(
        range(dalle.num_text_tokens_ext - dalle.text_seq_len, dalle.num_text_tokens_ext)
    )
    texts = [
        tokenizer.decode([int(t) for t in row], pad_tokens=pad_tokens)
        for row in text_tokens
    ]
    return text_tokens, texts
