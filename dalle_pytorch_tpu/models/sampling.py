"""Autoregressive sampling for DALL-E, TPU-native.

The reference samples by re-running the full forward pass over the whole
prefix for every generated token (dalle_pytorch.py:481-486) — O(L^2) attention
work per token. Here generation is ONE parallel ``DALLE.prefill_step`` pass
over the text prompt (filling every decode cache with MXU-shaped matmuls)
followed by a single ``lax.scan`` over the KV-cached ``DALLE.decode_step``
for the image positions — each step one (1 x L) attention per layer, the
whole sequence one XLA program. Priming beyond the text prompt is
teacher-forced inside the scan via ``known_len``. Randomness flows through
explicit PRNG keys; top-k fractional-threshold filtering, temperature,
image-token priming (reference dalle_pytorch.py:470-479) and CLIP reranking
(dalle_pytorch.py:503-505) all match the reference semantics.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops import kv_policy, paged_kv
from .dalle import DALLE, top_k_filter

# Cache-window growth granularity for the segmented decode scan below.
# None = batch-adaptive (the decode_tokens default); an int overrides:
# 0 disables segmentation (single full-extent scan), k > 0 grows the K/V
# caches every k positions.
DECODE_WINDOW_SEG = None

# Scan-body unroll for the decode loop (see the segmented-scan comment in
# decode_tokens).
DECODE_UNROLL = 4


def _format_ctx(cache_format: Optional[str]):
    """Pin the KV layout for a traced block when the caller asked for one;
    ``None`` leaves the policy (or an enclosing override) in charge."""
    if cache_format is None:
        return contextlib.nullcontext()
    return kv_policy.format_override(cache_format)


def init_decode_cache(
    dalle: DALLE, params, batch_size: int,
    cache_format: Optional[str] = None, kv_quant: Optional[str] = None,
):
    """Materialize the transformer's KV/shift caches for a batch.

    ``cache_format`` pins the KV layout ("paged" | "flat" | "4d");
    ``kv_quant`` the paged pools' storage quantization ("none" | "int8"
    — int8 content pools plus parallel per-(token, head) scale pools;
    ops/kv_policy.py). None defers each to its policy chain. An invalid
    value for either fails typed at resolution time
    (``InvalidKVFormatError``)."""
    token = jnp.zeros((batch_size,), dtype=jnp.int32)
    quant_ctx = (
        contextlib.nullcontext() if kv_quant is None
        else kv_policy.quant_override(kv_policy.resolve_quant(kv_quant))
    )
    with _format_ctx(cache_format), quant_ctx:
        _, mutated = dalle.apply(
            {"params": params},
            token,
            jnp.array(0, jnp.int32),
            method=DALLE.decode_step,
            mutable=["cache"],
        )
    return mutated["cache"]


def set_decode_offsets(cache, offsets):
    """Place each sequence of a PAGED decode cache at its own offset —
    the continuous-batching entry point (requests at different decode
    positions share one step). Rewrites every per-position index in the
    cache tree: the attention K/V write index (already (b,) for paged)
    and the token-shift ring index (scalar -> (b,)). The flat/4-D formats
    store a scalar index and cannot express ragged offsets — attention
    would broadcast the vector wrongly, so this guards against them.

    The caller owns cache CONTENTS: rows at positions >= offsets[i] must
    be zeros/stale-masked (true after init + per-sequence replay or
    ``merge_decode_caches``)."""
    leaves = jax.tree_util.tree_leaves_with_path(cache)
    leaf_keys = {getattr(p[-1], "key", None) for p, _ in leaves}
    if "cached_key" in leaf_keys:
        raise ValueError(
            "ragged decode offsets need the paged cache format "
            '(init_decode_cache(..., cache_format="paged"))'
        )
    if "gate_index" in leaf_keys:
        raise ValueError(
            "ragged decode offsets are unsupported for gMLP ('mlp') layers: "
            "the spatial-gate history (ops/layers.py:SpatialGatingUnit) "
            "indexes by a scalar absolute position"
        )
    offsets = jnp.asarray(offsets, jnp.int32)
    assert offsets.ndim == 1, f"offsets must be (b,), got {offsets.shape}"
    batches = {
        x.shape[0] for p, x in leaves
        if getattr(p[-1], "key", None) == "cached_key_pages"
    }
    if batches != {offsets.shape[0]}:
        raise ValueError(
            f"offsets length {offsets.shape[0]} != cache batch {sorted(batches)}"
            " — a mismatched vector would broadcast into wrong-position writes"
        )

    def fn(path, x):
        if getattr(path[-1], "key", None) in ("cache_index", "shift_index"):
            return offsets
        return x

    return jax.tree_util.tree_map_with_path(fn, cache)


def merge_decode_caches(caches):
    """Stack per-sequence PAGED decode caches (each batch-1, at its own
    decode offset) into one batched cache — how a continuous-batching
    serving loop admits a newly-prefilled request into a running batch.
    Batched leaves concatenate on axis 0; scalar indices (the token-shift
    ring's) stack into (b,) vectors. Paged-only, and no gMLP layers, for
    the same reasons as ``set_decode_offsets``."""
    for c in caches:
        keys = {
            getattr(p[-1], "key", None)
            for p, _ in jax.tree_util.tree_leaves_with_path(c)
        }
        if "cached_key" in keys:
            raise ValueError("merge_decode_caches requires paged caches")
        if "gate_index" in keys:
            raise ValueError(
                "merge_decode_caches cannot merge gMLP ('mlp') caches: the "
                "spatial-gate history indexes by a scalar absolute position"
            )

    row_offsets = []
    total = 0
    for c in caches:
        row_offsets.append(total)
        total += {
            x.shape[0]
            for p, x in jax.tree_util.tree_leaves_with_path(c)
            if getattr(p[-1], "key", None) == "cached_key_pages"
        }.pop()

    def merge(path, *leaves):
        if leaves[0].ndim == 0:
            return jnp.stack(leaves)
        if getattr(path[-1], "key", None) == "page_table":
            # tables hold GLOBAL ids (row * n_pages + page); each cache's
            # rows land at a new row offset in the merged pool, so its
            # row-local references shift by offset * n_pages
            n_p = leaves[0].shape[1]
            leaves = [
                t + off * n_p for t, off in zip(leaves, row_offsets)
            ]
        return jnp.concatenate(leaves, axis=0)

    return jax.tree_util.tree_map_with_path(merge, *caches)


def insert_decode_cache(batched, sub, slot: int):
    """Write a batch-1 PAGED decode cache into row ``slot`` of a batched
    cache — the fixed-slot admission primitive of the serving engine
    (serving/engine.py): a newly-prefilled request lands in a free slot of
    the running batch without rebuilding the whole cache the way
    ``merge_decode_caches`` does.

    Both trees must be fully vectorized (every per-position index a (b,)
    vector — run ``set_decode_offsets`` on each after init/prefill), so
    every leaf pairs as ``batched[slot] = sub[0]``. Returns the updated
    batched cache; the previous tenant's rows are fully overwritten (K/V
    pools, page table, indices, shift history), which is what makes a slot
    reset = inserting a pristine cache."""
    sub_leaves = jax.tree_util.tree_leaves_with_path(sub)
    keys = {getattr(p[-1], "key", None) for p, _ in sub_leaves}
    if "cached_key" in keys:
        raise ValueError("insert_decode_cache requires paged caches")
    if "gate_index" in keys:
        raise ValueError(
            "insert_decode_cache cannot place gMLP ('mlp') caches: the "
            "spatial-gate history indexes by a scalar absolute position"
        )
    for p, x in sub_leaves:
        if x.ndim == 0 or x.shape[0] != 1:
            raise ValueError(
                f"sub-cache leaf {p} is not batch-1-vectorized "
                f"(shape {getattr(x, 'shape', ())}); run set_decode_offsets "
                "on the prefilled cache first"
            )

    def fn(path, b_leaf, s_leaf):
        row = s_leaf[0]
        if getattr(path[-1], "key", None) == "page_table":
            # global-id rebase: the batch-1 cache's table references its
            # own (only) storage row; at slot ``slot`` those pages live
            # ``slot * n_pages`` further into the batched pool's flat view
            row = row + slot * b_leaf.shape[1]
        return b_leaf.at[slot].set(row)

    return jax.tree_util.tree_map_with_path(fn, batched, sub)


@partial(jax.jit, static_argnums=(0, 5, 8, 9, 10, 11))
def decode_tokens(
    dalle: DALLE,
    params,
    tokens: jnp.ndarray,
    known_len: int,
    key: jax.Array,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    mask: Optional[jnp.ndarray] = None,
    num_steps: Optional[int] = None,
    prefill_len: int = 0,
    window_seg: Optional[int] = None,
    cache_format: Optional[str] = None,
):
    """Run the decode scan over the internal token buffer.

    tokens: (b, n_internal) int32 — position 0 is <bos>; the first
    ``known_len`` positions are prompt (teacher-forced), the rest are filled by
    sampling. ``known_len`` is traced, so varying prompt/prime lengths reuse
    one compilation. Text positions hold remapped text ids, image positions
    hold un-offset image token ids. Scans ``num_steps`` (default
    n_internal - 1) input positions and returns the completed buffer.

    ``prefill_len`` (static): process that many leading positions in one
    parallel ``DALLE.prefill_step`` pass instead of sequential scan steps —
    callers must guarantee known_len >= prefill_len and prefill_len <=
    text_len_internal (image generation prefills the whole text prompt,
    cutting the sequential steps from n_internal-1 to image_seq_len).
    Note: prefill consumes ONE PRNG split for the whole block where the
    sequential path consumed one per position, so sampled tokens for a given
    key differ between prefill_len settings (logits and caches are
    bit-identical; only the key stream shifts).

    ``window_seg`` (static): cache-window growth granularity for the
    segmented scan — None defers to the ``DECODE_WINDOW_SEG`` module
    override and then the batch-adaptive default below; 0 disables
    segmentation. Passing it explicitly keeps the knob trace-visible
    (a mutated module global is ignored by already-cached jit traces).

    ``cache_format`` (static): the decode KV layout, "paged" | "flat" |
    "4d"; None defers to the batch-size policy (ops/kv_policy.py). Static
    so the format participates in the jit cache key; the override context
    wraps the whole traced body, so every layer's cache declaration sees
    the same pinned format.
    """
    b, n_internal = tokens.shape
    fmt = kv_policy.resolve_format(cache_format, b)
    with kv_policy.format_override(fmt):
        return _decode_tokens_body(
            dalle, params, tokens, known_len, key, filter_thres, temperature,
            mask, num_steps, prefill_len, window_seg,
        )


def _decode_tokens_body(
    dalle, params, tokens, known_len, key, filter_thres, temperature,
    mask, num_steps, prefill_len, window_seg,
):
    b, n_internal = tokens.shape
    steps = n_internal - 1 if num_steps is None else num_steps
    text_len_internal = dalle.text_len_internal
    ext = dalle.num_text_tokens_ext

    cache = init_decode_cache(dalle, params, b)

    # after a full-text-prompt prefill, every sampled position is an image
    # position whose text-vocab logits are masked (NEG_INF fill) — slicing to
    # the live image segment samples the same distribution (masked entries
    # rank below every real logit, so the full-vocab k gives the same
    # threshold) and shrinks the per-token top-k sort from total_tokens to
    # num_image_tokens wide; with the reference's fractional k it often
    # disappears entirely (k >= image vocab => no filtering). Like prefill,
    # this shifts the PRNG consumption (categorical draws over a narrower
    # array), so sampled tokens for a given key differ from the full-vocab
    # path while remaining distributionally identical.
    image_only = prefill_len == text_len_internal
    k_full = max(int((1 - filter_thres) * dalle.total_tokens), 1)

    def apply_sample(tokens, key, logits, i, sliced=False):
        """Sample the token at position i+1 from consumed-position-i logits
        (teacher-forced while i+1 < known_len). ``sliced`` marks logits that
        arrive already cut to the image vocab (decode_step image_only)."""
        key, sub = jax.random.split(key)
        filtered = (
            top_k_filter(logits if sliced else logits[:, ext:], k=k_full)
            if image_only
            else top_k_filter(logits, thres=filter_thres)
        )
        sample = jax.random.categorical(sub, filtered / temperature, axis=-1)
        nxt = i + 1
        if not image_only:
            sample = jnp.where(nxt >= text_len_internal, sample - ext, sample)
        prev = jax.lax.dynamic_slice_in_dim(tokens, nxt, 1, axis=1)[:, 0]
        new_val = jnp.where(nxt < known_len, prev, sample).astype(tokens.dtype)
        tokens = jax.lax.dynamic_update_slice(tokens, new_val[:, None], (0, nxt))
        return tokens, key

    start = 0
    if prefill_len > 1:
        logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            tokens[:, :prefill_len],
            mask,
            method=DALLE.prefill_step,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        tokens, key = apply_sample(tokens, key, logits, prefill_len - 1)
        start = prefill_len

    def step(carry, i):
        cache, tokens, key = carry
        tok_in = jax.lax.dynamic_slice_in_dim(tokens, i, 1, axis=1)[:, 0]
        logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            tok_in,
            i,
            mask,
            image_only=image_only,
            method=DALLE.decode_step,
            mutable=["cache"],
        )
        tokens, key = apply_sample(tokens, key, logits, i, sliced=image_only)
        return (mutated["cache"], tokens, key), None

    def resize_kv(cache, W):
        """Size every layer's K/V cache to W rows (truncate or zero-pad on
        the position axis). Attention sweeps whatever extent it is handed
        (ops/attention.py:_decode_attend), so a smaller ARRAY — not a
        sliced view, which XLA materializes as a per-step copy (measured
        +0.11 ms/token, v5e int8) — is what makes a short window cheap.
        Paged caches resize at PAGE granularity: pools and page tables
        truncate/grow in lockstep on the page axis (tables are identity
        inside a jitted generation — ops/paged_kv.py:identity_table — so
        surviving entries stay valid and grown entries extend the
        identity). Only the K/V caches resize: the token-shift history is
        already a fixed-size ring (ops/layers.py:PreShiftToken) and the
        gMLP gate history indexes by absolute position at full extent."""
        page = kv_policy.page_size()
        n_p = paged_kv.num_pages(W, page)

        def fn(path, x):
            key = getattr(path[-1], "key", None)
            if key in ("cached_key", "cached_value"):
                if x.shape[1] > W:
                    return x[:, :W]
                if x.shape[1] < W:
                    return jnp.pad(
                        x, [(0, 0), (0, W - x.shape[1])] + [(0, 0)] * (x.ndim - 2)
                    )
            elif key in paged_kv.POOL_LEAF_KEYS:
                # content AND scale pools truncate/grow in lockstep on
                # the page axis (the scale pools are pool-shaped with
                # feat = heads; ops/paged_kv.py)
                if x.shape[1] > n_p:
                    return x[:, :n_p]
                if x.shape[1] < n_p:
                    return jnp.pad(
                        x, [(0, 0), (0, n_p - x.shape[1]), (0, 0), (0, 0)]
                    )
            elif key == "page_table":
                # tables hold GLOBAL ids r * n_pages + i whose stride is
                # the pool's page axis — resizing the pool changes the
                # stride, so the identity is REBUILT, not sliced/extended
                # (identity is the in-jit invariant; ops/paged_kv.py)
                if x.shape[1] != n_p:
                    return paged_kv.identity_table(x.shape[0], n_p).astype(
                        x.dtype
                    )
            return x

        return jax.tree_util.tree_map_with_path(fn, cache)

    # The scan is SEGMENTED by cache extent: step i only ever reads cache
    # rows [0, i+1), so a segment ending at position e runs against K/V
    # caches truncated to ceil128(e) rows instead of the full seq_len —
    # identical attention (rows beyond the frontier are zeros under a False
    # mask column either way) at ~30% less sweep HBM traffic averaged over
    # image generation. Per-segment unrolling amortizes loop overhead in
    # the bandwidth-bound decode (measured ~2% p50 latency on v5e at
    # unroll=4).
    # Adaptive segmentation (measured, v5e-1 flagship, 2026-07): K/V sweep
    # traffic scales with batch while the per-segment overhead
    # (scan-boundary cache pads, extra program) is ~fixed, so frontier-sized
    # caches win whenever sweeps are a large share of the step. Measured
    # ms/token (batch 1) and tokens/sec (batched):
    #   int8 b1: seg 0 = 0.686 vs 0.704-0.709 segmented  -> seg 0
    #   bf16 b1: seg 512 = 0.917, seg 256 = 0.929, seg 0 = 1.219 -> seg 512
    #   int8 b8: seg 512 = 5136 vs 4569 unsegmented (+12%); seg 256/1024
    #            worse (4985/4921); int8 b32: 6381 vs 5644 (+13%) -> seg 512
    # Only quantized single-stream decode prefers no segmentation (int8
    # halves the weight stream, leaving the step latency-bound on the
    # serial op chain where the boundary programs only add overhead).
    seg = window_seg if window_seg is not None else DECODE_WINDOW_SEG
    if seg is None:
        seg = 0 if (b == 1 and getattr(dalle, "serve_quant", False)) else 512
    assert seg >= 0, f"window_seg must be >= 0 (0 disables segmentation), got {seg}"
    n_cache = dalle.text_len_internal + dalle.image_seq_len
    carry = (cache, tokens, key)
    s = start
    while s < steps:
        e = min(steps, (s // seg + 1) * seg) if seg else steps
        if seg:
            W = min(n_cache, -(-e // 128) * 128)
            carry = (resize_kv(carry[0], W), carry[1], carry[2])
        carry, _ = jax.lax.scan(
            step, carry, jnp.arange(s, e, dtype=jnp.int32), unroll=DECODE_UNROLL,
        )
        s = e
    _, tokens, _ = carry
    return tokens


def generate_image_tokens(
    dalle: DALLE,
    params,
    text: jnp.ndarray,
    key: jax.Array,
    *,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    prime_tokens: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
    window_seg: Optional[int] = None,
    cache_format: Optional[str] = None,
) -> jnp.ndarray:
    """text: (b, text_seq_len) raw ids -> sampled image token ids
    (b, image_seq_len)."""
    b = text.shape[0]
    text = text[:, : dalle.text_seq_len].astype(jnp.int32)
    # remap_text touches no params, so the unbound-module call is safe
    internal_text = dalle.remap_text(text)

    n_internal = dalle.text_len_internal + dalle.image_seq_len
    tokens = jnp.zeros((b, n_internal), dtype=jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, internal_text, (0, 0))

    known_len = dalle.text_len_internal
    if prime_tokens is not None:
        assert prime_tokens.shape[1] < dalle.image_seq_len, (
            "number of priming image tokens must be < image_seq_len"
        )
        tokens = jax.lax.dynamic_update_slice(
            tokens, prime_tokens.astype(jnp.int32), (0, dalle.text_len_internal)
        )
        known_len += int(prime_tokens.shape[1])

    tokens = decode_tokens(
        dalle, params, tokens, known_len, key,
        filter_thres=filter_thres, temperature=temperature, mask=mask,
        prefill_len=dalle.text_len_internal, window_seg=window_seg,
        cache_format=cache_format,
    )
    return tokens[:, dalle.text_len_internal :]


def generate_images(
    dalle: DALLE,
    params,
    vae,
    vae_variables,
    text: jnp.ndarray,
    key: jax.Array,
    *,
    clip=None,
    clip_variables=None,
    mask: Optional[jnp.ndarray] = None,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    img: Optional[jnp.ndarray] = None,
    num_init_img_tokens: Optional[int] = None,
):
    """Full text -> pixels pipeline (reference generate_images,
    dalle_pytorch.py:451-507): optional image priming with
    ``int(0.4375 * image_seq_len)`` tokens, scan-decode, VAE decode, optional
    CLIP rerank. ``vae`` / ``clip`` are flax modules sharing the reference's
    duck-type (get_codebook_indices / decode; __call__ similarity)."""
    text = text[:, : dalle.text_seq_len]  # rerank sees the same truncated text
    prime = None
    if img is not None:
        indices = vae.apply(vae_variables, img, method=type(vae).get_codebook_indices)
        n_prime = (
            int(0.4375 * dalle.image_seq_len)
            if num_init_img_tokens is None
            else num_init_img_tokens
        )
        prime = indices[:, :n_prime]

    img_seq = generate_image_tokens(
        dalle, params, text, key,
        filter_thres=filter_thres, temperature=temperature,
        prime_tokens=prime, mask=mask,
    )
    images = vae.apply(vae_variables, img_seq, method=type(vae).decode)

    if clip is not None:
        scores = clip.apply(clip_variables, text, images)
        return images, scores
    return images


def generate_texts(
    dalle: DALLE,
    params,
    key: jax.Array,
    prompt_tokens: Optional[jnp.ndarray] = None,
    *,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    tokenizer=None,
):
    """Text-only completion (reference generate_texts,
    dalle_pytorch.py:403-449): start from <bos> (plus an optional encoded
    prompt) and sample out to text_seq_len tokens. Returns (tokens, texts) —
    texts only when a tokenizer with pad-aware decode is supplied."""
    if prompt_tokens is None:
        prompt_tokens = jnp.zeros((1, 1), dtype=jnp.int32)
    b, p = prompt_tokens.shape

    tokens = jnp.zeros((b, dalle.text_len_internal + dalle.image_seq_len), jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, prompt_tokens.astype(jnp.int32), (0, 0))

    tokens = decode_tokens(
        dalle, params, tokens, p, key,
        filter_thres=filter_thres, temperature=temperature,
        num_steps=dalle.text_seq_len - 1,
    )
    text_tokens = tokens[:, : dalle.text_seq_len]

    if tokenizer is None:
        return text_tokens, None
    pad_tokens = set(
        range(dalle.num_text_tokens_ext - dalle.text_seq_len, dalle.num_text_tokens_ext)
    )
    texts = [
        tokenizer.decode([int(t) for t in row], pad_tokens=pad_tokens)
        for row in text_tokens
    ]
    return text_tokens, texts
