"""VQGAN wrapper (taming-transformers), re-owned in flax.

Capability parity with the reference's ``VQGanVAE`` (vae.py:135-220): load a
published taming VQModel/GumbelVQ checkpoint + yaml config, encode images to
codebook indices ([-1,1] input scaling, vae.py:198-205), decode indices via
codebook matmul + the conv decoder ([-1,1] -> [0,1] clamp, vae.py:207-217),
``num_layers`` derived from the config downsample factor (vae.py:177-178),
and a frozen, inference-only forward (vae.py:219-220).

This is the reference's main perf lever: the default f=16 model drops the
image sequence 1024 -> 256, a ~16x attention-cost cut (README.md:189).

The graphs (taming's ddconfig-driven conv encoder/decoder with GroupNorm +
swish ResNet blocks, single-head spatial attention at configured
resolutions, VectorQuantizer / GumbelQuantize codebooks) are rebuilt NHWC
for the MXU; layers are named by their torch dotted path (dots ->
underscores) so the checkpoint converter is a mechanical rename + OIHW->HWIO
transpose. Config parsing accepts the published OmegaConf yaml files via
plain pyyaml (no omegaconf dependency).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

Dtype = Any

VQGAN_VAE_CONFIG_URL = (
    "https://heibox.uni-heidelberg.de/d/8088892a516d4e3baf92/files/"
    "?p=%2Fconfigs%2Fmodel.yaml&dl=1"
)
VQGAN_VAE_MODEL_URL = (
    "https://heibox.uni-heidelberg.de/d/8088892a516d4e3baf92/files/"
    "?p=%2Fckpts%2Flast.ckpt&dl=1"
)


def _swish(x):
    return x * jax.nn.sigmoid(x)


def _group_norm(name: str, param_dtype):
    # taming Normalize: GroupNorm(32, eps=1e-6, affine=True)
    return nn.GroupNorm(
        num_groups=32, epsilon=1e-6, dtype=jnp.float32, param_dtype=param_dtype,
        name=name,
    )


# flat naming: children are created under the torch dotted path with dots
# swapped for underscores ("down.0.block.1.conv1" -> "down_0_block_1_conv1"),
# which is exactly what the checkpoint converter emits
def _flat(name: str) -> str:
    return name.replace(".", "_")


class _TamingCoder(nn.Module):
    """Shared machinery for the taming encoder/decoder: flat-named conv /
    norm children matching the torch checkpoint's dotted paths."""

    ch: int
    ch_mult: Tuple[int, ...]
    num_res_blocks: int
    attn_resolutions: Tuple[int, ...]
    resolution: int
    z_channels: int
    out_ch: int = 3
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _conv(self, name: str, features: int, kernel: int = 3, stride: int = 1):
        return nn.Conv(
            features,
            (kernel, kernel),
            strides=(stride, stride),
            padding="VALID" if stride == 2 else kernel // 2,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name=_flat(name),
        )

    def _resnet_block(self, prefix: str, x, out_ch: int):
        h = _swish(self._norm_apply(f"{prefix}.norm1", x))
        h = self._conv(f"{prefix}.conv1", out_ch)(h)
        h = _swish(self._norm_apply(f"{prefix}.norm2", h))
        h = self._conv(f"{prefix}.conv2", out_ch)(h)
        if x.shape[-1] != out_ch:
            x = self._conv(f"{prefix}.nin_shortcut", out_ch, kernel=1)(x)
        return x + h

    def _attn_block(self, prefix: str, x):
        b, hh, ww, c = x.shape
        h = self._norm_apply(f"{prefix}.norm", x)
        q = self._conv(f"{prefix}.q", c, kernel=1)(h).reshape(b, hh * ww, c)
        k = self._conv(f"{prefix}.k", c, kernel=1)(h).reshape(b, hh * ww, c)
        v = self._conv(f"{prefix}.v", c, kernel=1)(h).reshape(b, hh * ww, c)
        w = jnp.einsum("bqc,bkc->bqk", q, k, preferred_element_type=jnp.float32)
        w = jax.nn.softmax(w * (c**-0.5), axis=-1).astype(v.dtype)
        h = jnp.einsum("bqk,bkc->bqc", w, v).reshape(b, hh, ww, c)
        return x + self._conv(f"{prefix}.proj_out", c, kernel=1)(h)

    def _norm_apply(self, name: str, x):
        return _group_norm(_flat(name), self.param_dtype)(
            x.astype(jnp.float32)
        ).astype(x.dtype)


class TamingEncoder(_TamingCoder):
    """conv_in -> per-level [ResnetBlock x n (+ attn at configured res),
    downsample] -> mid (block, attn, block) -> GroupNorm/swish/conv_out."""

    @nn.compact
    def __call__(self, x):
        curr_res = self.resolution
        h = self._conv("conv_in", self.ch)(x)
        for i, mult in enumerate(self.ch_mult):
            out_ch = self.ch * mult
            for j in range(self.num_res_blocks):
                h = self._resnet_block(f"down.{i}.block.{j}", h, out_ch)
                if curr_res in self.attn_resolutions:
                    h = self._attn_block(f"down.{i}.attn.{j}", h)
            if i != len(self.ch_mult) - 1:
                # taming Downsample: asymmetric (0,1,0,1) pad + 3x3 stride-2
                h = jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0)))
                h = self._conv(f"down.{i}.downsample.conv", out_ch, 3, 2)(h)
                curr_res //= 2

        block_in = self.ch * self.ch_mult[-1]
        h = self._resnet_block("mid.block_1", h, block_in)
        h = self._attn_block("mid.attn_1", h)
        h = self._resnet_block("mid.block_2", h, block_in)

        h = _swish(self._norm_apply("norm_out", h))
        return self._conv("conv_out", self.z_channels)(h)


class TamingDecoder(_TamingCoder):
    """conv_in -> mid -> reversed levels [ResnetBlock x (n+1) (+ attn),
    nearest-2x upsample + conv] -> GroupNorm/swish/conv_out."""

    @nn.compact
    def __call__(self, z):
        num_levels = len(self.ch_mult)
        block_in = self.ch * self.ch_mult[-1]
        curr_res = self.resolution // 2 ** (num_levels - 1)

        h = self._conv("conv_in", block_in)(z)
        h = self._resnet_block("mid.block_1", h, block_in)
        h = self._attn_block("mid.attn_1", h)
        h = self._resnet_block("mid.block_2", h, block_in)

        for i in reversed(range(num_levels)):
            out_ch = self.ch * self.ch_mult[i]
            for j in range(self.num_res_blocks + 1):
                h = self._resnet_block(f"up.{i}.block.{j}", h, out_ch)
                if curr_res in self.attn_resolutions:
                    h = self._attn_block(f"up.{i}.attn.{j}", h)
            if i != 0:
                h = jnp.repeat(jnp.repeat(h, 2, axis=1), 2, axis=2)
                h = self._conv(f"up.{i}.upsample.conv", out_ch)(h)
                curr_res *= 2

        h = _swish(self._norm_apply("norm_out", h))
        return self._conv("conv_out", self.out_ch)(h)


class VQQuantizer(nn.Module):
    """VectorQuantizer codebook surface: nearest-L2 indices (encode) +
    embedding lookup (decode). Training losses live with a VQGAN trainer,
    not here — the wrapper is frozen."""

    n_embed: int
    embed_dim: int
    param_dtype: Any = jnp.float32

    def setup(self):
        # torch layout (n_embed, embed_dim); declared in setup so encode-only
        # and decode-only entry points both materialize it
        self.embedding = self.param(
            "embedding",
            nn.initializers.uniform(scale=2.0 / self.n_embed),
            (self.n_embed, self.embed_dim),
            self.param_dtype,
        )

    def __call__(self, z):
        """z: (b, h, w, c) -> flat (b, h*w) nearest-codebook indices."""
        b = z.shape[0]
        flat = z.reshape(b, -1, self.embed_dim).astype(jnp.float32)
        e = self.embedding.astype(jnp.float32)
        # ||z - e||^2 = z^2 - 2 z.e + e^2 (argmin over codes)
        d = (
            jnp.sum(flat**2, -1, keepdims=True)
            - 2 * jnp.einsum("bnd,kd->bnk", flat, e)
            + jnp.sum(e**2, -1)[None, None]
        )
        return jnp.argmin(d, axis=-1).astype(jnp.int32)

    def lookup(self, indices):
        return jnp.take(self.embedding, indices, axis=0)


class GumbelQuantizer(nn.Module):
    """GumbelQuantize codebook surface: 1x1 conv to logits for encode
    (hard argmax at inference), separate embed table for decode."""

    n_embed: int
    embed_dim: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def setup(self):
        self.proj = nn.Conv(
            self.n_embed, (1, 1), dtype=self.dtype, param_dtype=self.param_dtype
        )
        self.embed = self.param(
            "embed",
            nn.initializers.normal(1.0),
            (self.n_embed, self.embed_dim),
            self.param_dtype,
        )

    def __call__(self, z):
        b = z.shape[0]
        logits = self.proj(z)
        return jnp.argmax(logits, axis=-1).reshape(b, -1).astype(jnp.int32)

    def lookup(self, indices):
        return jnp.take(self.embed, indices, axis=0)


class VQGanVAE(nn.Module):
    """Frozen taming VQGAN with the DiscreteVAE duck-type surface
    (reference vae.py:150-220). Defaults are the published imagenet f=16
    1024-codebook model the reference downloads by default (vae.py:155-158)
    — image seq 256 instead of the dVAE's 1024."""

    image_size: int = 256
    ch: int = 128
    ch_mult: Tuple[int, ...] = (1, 1, 2, 2, 4)
    num_res_blocks: int = 2
    attn_resolutions: Tuple[int, ...] = (16,)
    z_channels: int = 256
    n_embed: int = 1024
    embed_dim: int = 256
    gumbel: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    normalization = None  # decode output is already [0, 1]

    @property
    def num_layers(self) -> int:
        """Downsample count; the reference derives the same value from
        resolution / attn_resolution (vae.py:177-178)."""
        return len(self.ch_mult) - 1

    @property
    def num_tokens(self) -> int:
        return self.n_embed

    @property
    def fmap_size(self) -> int:
        return self.image_size // (2**self.num_layers)

    @property
    def image_seq_len(self) -> int:
        return self.fmap_size**2

    def setup(self):
        kw = dict(
            ch=self.ch,
            ch_mult=tuple(self.ch_mult),
            num_res_blocks=self.num_res_blocks,
            attn_resolutions=tuple(self.attn_resolutions),
            resolution=self.image_size,
            z_channels=self.z_channels,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        self.encoder = TamingEncoder(**kw)
        self.decoder = TamingDecoder(**kw)
        # GumbelVQ passes embed_dim=z_channels to the base VQModel, so its
        # quant/post-quant convs stay z->z (taming models/vqgan.py GumbelVQ)
        inner = self.z_channels if self.gumbel else self.embed_dim
        self.quant_conv = nn.Conv(
            inner, (1, 1), dtype=self.dtype, param_dtype=self.param_dtype
        )
        self.post_quant_conv = nn.Conv(
            self.z_channels, (1, 1), dtype=self.dtype, param_dtype=self.param_dtype
        )
        if self.gumbel:
            self.quantize = GumbelQuantizer(
                n_embed=self.n_embed, embed_dim=self.embed_dim,
                dtype=self.dtype, param_dtype=self.param_dtype,
            )
        else:
            self.quantize = VQQuantizer(
                n_embed=self.n_embed, embed_dim=self.embed_dim,
                param_dtype=self.param_dtype,
            )

    def get_codebook_indices(self, img: jnp.ndarray) -> jnp.ndarray:
        """img (b, h, w, 3) in [0, 1] -> (b, fmap**2) indices
        (reference vae.py:198-205: [-1, 1] scaling then model.encode)."""
        x = 2.0 * img - 1.0
        h = self.quant_conv(self.encoder(x.astype(self.dtype)))
        return self.quantize(h)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        """Indices (b, n) -> (b, H, W, 3) pixels in [0, 1]
        (reference vae.py:207-217)."""
        b, n = img_seq.shape
        f = int(math.isqrt(n))
        z = self.quantize.lookup(img_seq).reshape(b, f, f, self.embed_dim)
        dec = self.decoder(self.post_quant_conv(z.astype(self.dtype)))
        return (jnp.clip(dec.astype(jnp.float32), -1.0, 1.0) + 1.0) * 0.5

    def __call__(self, img):
        raise NotImplementedError(
            "VQGanVAE is frozen and inference-only (reference vae.py:219-220)"
        )


# -------------------------------------------------------------- conversion


def convert_vqgan_checkpoint(sd: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """taming state dict -> VQGanVAE flax param tree. Mechanical: dotted
    torch paths become flat underscore names inside encoder/decoder; conv
    weights transpose OIHW -> HWIO; GroupNorm weight -> scale. Loss-head /
    EMA keys are skipped (the wrapper is inference-only)."""
    params: Dict[str, Any] = {}

    def put(path, leaf, value):
        node = params
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node.setdefault(path[-1], {})[leaf] = jnp.asarray(value)

    for key, v in sd.items():
        parts = key.split(".")
        top = parts[0]
        if top in ("loss", "temperature_scheduler", "used", "colorize"):
            continue
        leaf = parts[-1]
        if leaf == "weight":
            if v.ndim == 4:
                leaf, v = "kernel", np.transpose(v, (2, 3, 1, 0))
            elif v.ndim == 1:
                leaf = "scale"
        elif leaf != "bias":
            continue

        if top in ("encoder", "decoder"):
            put((top, "_".join(parts[1:-1])), leaf, v)
        elif top in ("quant_conv", "post_quant_conv"):
            put((top,), leaf, v)
        elif top == "quantize":
            if parts[1] in ("embedding", "embed") and parts[-1] == "weight":
                # 2-d table: keep torch layout (n_embed, embed_dim)
                params.setdefault("quantize", {})[parts[1]] = jnp.asarray(v)
            elif parts[1] == "proj":
                put(("quantize", "proj"), leaf, v)
        # anything else (scheduler buffers etc.) is dropped
    return params


def _ddconfig_from_yaml(config_path: str) -> Tuple[dict, int, int, bool]:
    """Parse a taming OmegaConf yaml (reference loads it via omegaconf,
    vae.py:165): -> (ddconfig, n_embed, embed_dim, is_gumbel)."""
    import yaml

    with open(config_path) as f:
        cfg = yaml.safe_load(f)
    model = cfg["model"]
    target = model.get("target", "")
    p = model["params"]
    return p["ddconfig"], int(p["n_embed"]), int(p["embed_dim"]), (
        "Gumbel" in target or "gumbel" in target
    )


def load_vqgan_vae(
    config_path: Optional[str] = None,
    model_path: Optional[str] = None,
    dtype: Any = jnp.float32,
):
    """(VQGanVAE, params) from a taming config yaml + checkpoint, mirroring
    reference vae.py:150-174 (default = published f16/1024 model via the
    rank-aware download cache)."""
    from .pretrained import download, load_torch_checkpoint

    if config_path is None:
        config_path = str(download(VQGAN_VAE_CONFIG_URL))
    if model_path is None:
        model_path = str(download(VQGAN_VAE_MODEL_URL))

    dd, n_embed, embed_dim, gumbel = _ddconfig_from_yaml(config_path)
    vae = VQGanVAE(
        image_size=int(dd["resolution"]),
        ch=int(dd["ch"]),
        ch_mult=tuple(dd["ch_mult"]),
        num_res_blocks=int(dd["num_res_blocks"]),
        attn_resolutions=tuple(dd["attn_resolutions"]),
        z_channels=int(dd["z_channels"]),
        n_embed=n_embed,
        embed_dim=embed_dim,
        gumbel=gumbel,
        dtype=dtype,
    )
    params = convert_vqgan_checkpoint(load_torch_checkpoint(model_path))
    return vae, params
