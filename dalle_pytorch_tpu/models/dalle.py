"""DALL-E: joint text->image autoregressive transformer, TPU-native.

Capability parity with the reference's ``DALLE`` (dalle_pytorch.py:309-585):
per-position unique padding tokens, <bos> prepend, text/image embedding concat,
static text-vs-image logits mask, and the weighted split cross-entropy loss —
rebuilt as a functional flax module:

- the model consumes **image token ids**, not raw pixels: VAE encode is a
  frozen no-grad lookup in the reference (dalle_pytorch.py:533-540) and lives
  outside the trained graph here (trainers call ``vae.get_codebook_indices``
  under ``stop_gradient`` and feed tokens), so the VAE is never entangled in
  the DALLE parameter pytree;
- the logits mask is a static numpy constant baked at trace time
  (reference registers a buffer, dalle_pytorch.py:388-399);
- ``decode_step`` runs one token through the KV-cached transformer for
  O(seq) per-token sampling — the reference re-runs the full prefix per token
  (dalle_pytorch.py:481-486).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from ..ops.layers import AxialPositionalEmbedding, divide_max
from .transformer import Transformer

Dtype = Any

NEG_INF = -0.7 * float(np.finfo(np.float32).max)


def top_k_filter(
    logits: jnp.ndarray, thres: float = 0.5, k: Optional[int] = None
) -> jnp.ndarray:
    """Keep the top ``max(int((1-thres)*vocab), 1)`` logits, fill the rest with
    -inf (reference top_k, dalle_pytorch.py:50-56).

    ``k`` overrides the fraction-derived count — callers that pre-slice the
    logits to a live vocab segment pass the FULL-vocab-derived k so the
    threshold matches the reference exactly; k >= width means no filtering
    (and skips the top-k sort entirely)."""
    num_logits = logits.shape[-1]
    if k is None:
        k = max(int((1 - thres) * num_logits), 1)
    if k >= num_logits:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


class DALLE(nn.Module):
    """Text+image autoregressive LM over a mixed discrete vocabulary.

    ``num_text_tokens`` is the raw text vocab; internally it is extended by
    ``text_seq_len`` per-position padding ids (reference dalle_pytorch.py:338).
    """

    dim: int
    depth: int
    num_text_tokens: int = 10000
    text_seq_len: int = 256
    num_image_tokens: int = 512
    image_fmap_size: int = 32
    heads: int = 8
    dim_head: int = 64
    reversible: bool = False
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    attn_types: Optional[Tuple[str, ...]] = None
    loss_img_weight: float = 7.0
    stable: bool = False
    shift_tokens: bool = True
    # extra token-shift ring rows (speculative-decode rollback slack; see
    # ops/layers.py:PreShiftToken.pad) — cache-shape only, parameters are
    # identical at every value, so a serving engine may clone the model
    # with a wider ring without touching the checkpoint
    shift_pad: int = 0
    rotary_emb: bool = True
    remat: bool = False
    sparse_layout_seed: int = 0
    use_flash: bool = True
    sp_axis: Optional[str] = None
    pp_axis: Optional[str] = None
    pp_microbatches: int = 4
    ff_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    serve_quant: bool = False
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    # ------------------------------------------------------------ derived

    @property
    def image_seq_len(self) -> int:
        return self.image_fmap_size**2

    @property
    def num_text_tokens_ext(self) -> int:
        return self.num_text_tokens + self.text_seq_len

    @property
    def total_tokens(self) -> int:
        return self.num_text_tokens_ext + self.num_image_tokens

    @property
    def total_seq_len(self) -> int:
        """Transformer input length (last token never fed, reference
        dalle_pytorch.py:554-556)."""
        return self.text_seq_len + self.image_seq_len

    @property
    def text_len_internal(self) -> int:
        """Text positions including <bos>."""
        return self.text_seq_len + 1

    def logits_mask_np(self) -> np.ndarray:
        """(total_seq_len, total_tokens) bool, True = FORBIDDEN: text positions
        may only predict text tokens, image positions image tokens (reference
        dalle_pytorch.py:388-399)."""
        seq = np.arange(self.total_seq_len)[:, None]
        logit = np.arange(self.total_tokens)[None, :]
        return ((seq >= self.text_seq_len) & (logit < self.num_text_tokens_ext)) | (
            (seq < self.text_seq_len) & (logit >= self.num_text_tokens_ext)
        )

    # -------------------------------------------------------------- setup

    def setup(self):
        from ..ops.layers import serving_embed

        self.text_emb = serving_embed(
            self.serve_quant, self.num_text_tokens_ext, self.dim,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )
        self.image_emb = serving_embed(
            self.serve_quant, self.num_image_tokens, self.dim,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )
        if not self.rotary_emb:
            self.text_pos_emb = nn.Embed(
                self.text_len_internal, self.dim, param_dtype=self.param_dtype
            )
            self.image_pos_emb = AxialPositionalEmbedding(
                dim=self.dim,
                shape=(self.image_fmap_size, self.image_fmap_size),
                param_dtype=self.param_dtype,
            )

        self.transformer = Transformer(
            dim=self.dim,
            depth=self.depth,
            seq_len=self.total_seq_len,
            reversible=self.reversible,
            causal=True,
            heads=self.heads,
            dim_head=self.dim_head,
            attn_dropout=self.attn_dropout,
            ff_dropout=self.ff_dropout,
            attn_types=self.attn_types,
            image_fmap_size=self.image_fmap_size,
            stable=self.stable,
            shift_tokens=self.shift_tokens,
            shift_pad=self.shift_pad,
            rotary_emb=self.rotary_emb,
            remat=self.remat,
            sparse_layout_seed=self.sparse_layout_seed,
            use_flash=self.use_flash,
            sp_axis=self.sp_axis,
            pp_axis=self.pp_axis,
            pp_microbatches=self.pp_microbatches,
            ff_experts=self.ff_experts,
            moe_every=self.moe_every,
            moe_capacity_factor=self.moe_capacity_factor,
            quant=self.serve_quant,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        self.final_norm = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype)
        # the vocab projection runs in compute dtype — in f32 this one matmul
        # (n x dim x ~18k vocab) would cost more MXU time than a whole layer;
        # the loss upcasts the logits to f32 before log_softmax
        from ..ops.layers import serving_dense

        self.to_logits = serving_dense(
            self.serve_quant, self.total_tokens,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )

    # ------------------------------------------------------------- helpers

    def remap_text(self, text: jnp.ndarray) -> jnp.ndarray:
        """Give each padding-0 text position its own unique token id and
        prepend <bos>=0 (reference dalle_pytorch.py:521-526)."""
        text_range = jnp.arange(self.text_seq_len, dtype=text.dtype) + (
            self.num_text_tokens_ext - self.text_seq_len
        )
        text = jnp.where(text == 0, text_range, text)
        return jnp.pad(text, ((0, 0), (1, 0)))  # <bos> = 0

    def _full_key_mask(self, mask: Optional[jnp.ndarray], n: int) -> Optional[jnp.ndarray]:
        """Text padding mask (b, text_seq_len) -> (b, n) key mask over the
        internal [bos, text, image] sequence."""
        if mask is None:
            return None
        b = mask.shape[0]
        bos = jnp.ones((b, 1), dtype=bool)
        img = jnp.ones((b, self.image_seq_len), dtype=bool)
        return jnp.concatenate((bos, mask, img), axis=1)[:, :n]

    def _head(self, out: jnp.ndarray) -> jnp.ndarray:
        if self.stable:
            out = divide_max(out)
        return self.to_logits(self.final_norm(out)).astype(jnp.float32)

    def _head_image(self, out: jnp.ndarray) -> jnp.ndarray:
        """Image-vocab-only head: the ``[ext:]`` column slice of the
        ``to_logits`` matvec, for decode steps that can only emit image
        tokens (every post-prefill step of image generation). Streams ~55%
        fewer head-weight bytes per token than the full head. The slice
        starts at the 128-aligned column below ``ext`` so the (int8 or bf16)
        kernel read stays tile-aligned; the few extra text columns are
        dropped from the result. The dequant/matvec arithmetic itself lives
        in ``dense_apply_columns`` (ops/layers.py), the one shared contract
        with QuantDense — this sliced head cannot diverge from the full
        head's math."""
        from ..ops.layers import dense_apply_columns

        if self.stable:
            out = divide_max(out)
        normed = self.final_norm(out)
        if self.is_initializing():
            self.to_logits(normed[:, :1])  # materialize the head params
        p = self.variables["params"]["to_logits"]
        ext = self.num_text_tokens_ext
        lo = (ext // 128) * 128
        logits = dense_apply_columns(p, normed, lo, self.dtype)
        return logits[..., ext - lo :].astype(jnp.float32)

    # ------------------------------------------------------------- forward

    def __call__(
        self,
        text: jnp.ndarray,
        image: Optional[jnp.ndarray] = None,
        mask: Optional[jnp.ndarray] = None,
        return_loss: bool = False,
        deterministic: bool = True,
    ):
        """text: (b, text_seq_len) int ids; image: (b, <=image_seq_len) token
        ids in [0, num_image_tokens). Returns logits (b, n, total_tokens) or
        the weighted CE loss (reference dalle_pytorch.py:509-585)."""
        assert text.shape[-1] == self.text_seq_len, (
            f"text length {text.shape[-1]} != text_seq_len {self.text_seq_len}"
        )
        text = self.remap_text(text)
        tokens = self.text_emb(text)
        if not self.rotary_emb:
            tokens = tokens + self.text_pos_emb(jnp.arange(self.text_len_internal))[None]

        if image is not None and image.shape[1] > 0:
            image_tokens = self.image_emb(image)
            if not self.rotary_emb:
                image_tokens = image_tokens + self.image_pos_emb(
                    image_tokens.shape[1]
                ).astype(image_tokens.dtype)
            tokens = jnp.concatenate((tokens, image_tokens), axis=1)

        # drop the trailing token: it never predicts anything
        if tokens.shape[1] > self.total_seq_len:
            tokens = tokens[:, : self.total_seq_len]
        n = tokens.shape[1]

        x = tokens.astype(self.dtype)
        if self.sp_axis is not None and not self.is_initializing():
            from ..parallel.context import constrain_seq_sharded

            x = constrain_seq_sharded(x, self.sp_axis, seq_dim=1)
        out = self.transformer(
            x,
            mask=self._full_key_mask(mask, n),
            deterministic=deterministic,
        )
        if self.stable:
            out = divide_max(out)
        normed = self.final_norm(out)

        if not return_loss:
            logits = self.to_logits(normed)  # compute dtype
            lmask = jnp.asarray(self.logits_mask_np()[:n])[None]
            return jnp.where(lmask, NEG_INF, logits.astype(jnp.float32))

        if self.serve_quant:
            raise ValueError(
                "serve_quant is an inference-only mode (int8 kernels receive "
                "no meaningful gradients); train with serve_quant=False and "
                "quantize the checkpoint via utils/quantize.py"
            )
        assert image is not None, "when training, image tokens must be supplied"
        assert image.shape[1] == self.image_seq_len, (
            f"the loss needs the full image sequence, got {image.shape[1]} of "
            f"{self.image_seq_len} tokens"
        )
        return self._split_head_loss(normed, text, image)

    def _split_head_loss(self, normed, text, image):
        """Weighted split CE with a block-diagonal head.

        The logits mask is block-diagonal — text positions may only predict
        text-vocab tokens, image positions image-vocab tokens (reference
        dalle_pytorch.py:388-399) — so masked logits have softmax probability
        0 and gradient 0. Computing only the live blocks of the ``to_logits``
        matmul is therefore EXACTLY the reference's masked cross-entropy
        (same loss, same gradients) at under half the head FLOPs: n x vocab
        becomes text_seq x text_vocab + image_seq x image_vocab. The CE uses
        logsumexp directly so no (b, n, vocab) f32 log-prob array is ever
        materialized (the f32 cast fuses into the reduction).
        """
        if self.is_initializing():
            self.to_logits(normed[:, :1])  # materialize the head params
        p = self.variables["params"]["to_logits"]
        W = jnp.asarray(p["kernel"], self.dtype)
        b_ = jnp.asarray(p["bias"], self.dtype)
        ext = self.num_text_tokens_ext
        tl = self.text_seq_len
        h = normed.astype(self.dtype)

        def segment_ll(hidden, cols, labels):
            logits = hidden @ W[:, cols] + b_[cols]
            lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
            picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
            return picked.astype(jnp.float32) - lse

        ll_text = segment_ll(h[:, :tl], slice(None, ext), text[:, 1:])
        ll_img = segment_ll(h[:, tl:], slice(ext, None), image)
        loss_text = -ll_text.mean()
        loss_img = -ll_img.mean()
        return (loss_text + self.loss_img_weight * loss_img) / (self.loss_img_weight + 1)

    # --------------------------------------------------------------- decode

    def prefill_step(
        self,
        tokens: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        image_only: bool = False,
    ) -> jnp.ndarray:
        """Process the first T text positions in ONE parallel pass, filling
        every decode cache (K/V, token-shift, gMLP gate), and return
        (b, total_tokens) logits predicting position T.

        The reference decodes the whole prompt token-by-token inside its
        sampling loop (dalle_pytorch.py:481-486); a parallel prefill removes
        those T sequential steps and runs MXU-shaped matmuls instead.
        tokens: (b, T) REMAPPED text ids (bos included), T <= text_len_internal
        static; equivalent to T sequential ``decode_step`` calls.

        ``image_only`` (static) requires the block to cover the WHOLE
        prompt (T == text_len_internal): position T is then the first
        image position, whose logits-mask row permits exactly the image
        vocab, so only the image-vocab head columns are computed
        (``_head_image`` — the same measured serving optimization as
        ``decode_step``'s flag, bit-equal to the full head's ``[ext:]``
        slice) and (b, num_image_tokens) logits return with no mask/where
        chain.
        """
        b, T = tokens.shape
        assert T <= self.text_len_internal, (
            f"prefill covers text positions only, got {T} > {self.text_len_internal}"
        )
        emb = self.text_emb(tokens)
        if not self.rotary_emb:
            emb = emb + self.text_pos_emb(jnp.arange(T))[None]

        out = self.transformer(
            emb.astype(self.dtype),
            mask=self._full_key_mask(mask, self.text_len_internal + self.image_seq_len),
            deterministic=True,
            decode=True,
        )
        if image_only:
            assert T == self.text_len_internal, (
                "image_only prefill requires the full prompt: position T "
                "must be the first image position"
            )
            return self._head_image(out[:, -1:])[:, 0]
        logits = self._head(out[:, -1:])[:, 0]
        mask_row = jnp.asarray(self.logits_mask_np())[T - 1 : T]
        return jnp.where(mask_row, NEG_INF, logits)

    def prefill_chunk(
        self,
        tokens: jnp.ndarray,
        start: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        return_logits: bool = True,
        image_only: bool = False,
    ):
        """Process text positions [start, start + c) of the prompt against
        the ALREADY-WRITTEN decode-cache prefix — one budget-bounded slice
        of a prefill, so a serving loop can interleave prompt processing
        with decode iterations instead of stalling every active slot for
        the whole monolithic ``prefill_step``.

        tokens: (b, c) REMAPPED text ids (bos included) for positions
        start..start+c; ``start`` is traced, so every chunk of one width
        shares a compilation (widths: the configured chunk size plus at
        most two ragged tail widths). The attention math is exactly the
        shared block path — ``ops/attention.py:cache_block_attend`` over
        the ``paged_kv.gather`` view of the page tables, with the chunk's
        per-position pattern-mask rows selecting the cache prefix plus the
        in-chunk causal block — so a sequence of ``prefill_chunk`` calls
        covering [0, T) produces a cache BIT-identical to one
        ``prefill_step`` over the same tokens, provided no chunk is a
        batch-1 single token (its PROJECTION matmuls would run as M=1
        matvecs accumulating ~1 ulp differently; the attention core
        itself pads width-1 blocks — ``cache_block_attend``). Pinned by
        tests/test_chunked_prefill.

        Returns (b, total_tokens) logits predicting position start + c
        when ``return_logits`` (the final chunk of a prompt samples the
        first image token from them, matching ``prefill_step``'s head
        row), else None — intermediate chunks skip the head entirely.
        ``image_only`` (static; implies return_logits) requires the chunk
        to END the prompt (start + c == T, unassertable on the traced
        start — callers guarantee it) and computes only the image-vocab
        head columns, exactly like ``prefill_step``'s flag.
        """
        b, c = tokens.shape
        assert c <= self.text_len_internal, (
            f"prefill chunks cover text positions only, got {c} > "
            f"{self.text_len_internal}"
        )
        start = jnp.asarray(start, jnp.int32)
        emb = self.text_emb(tokens)
        if not self.rotary_emb:
            emb = emb + self.text_pos_emb(start + jnp.arange(c))[None]

        out = self.transformer(
            emb.astype(self.dtype),
            mask=self._full_key_mask(mask, self.text_len_internal + self.image_seq_len),
            deterministic=True,
            decode=True,
        )
        if image_only:
            return self._head_image(out[:, -1:])[:, 0]
        if not return_logits:
            return None
        logits = self._head(out[:, -1:])[:, 0]
        lm = jnp.asarray(self.logits_mask_np())
        mask_row = jax.lax.dynamic_slice_in_dim(lm, start + c - 1, 1, axis=0)
        return jnp.where(mask_row, NEG_INF, logits)

    def fused_step(
        self,
        tokens: jnp.ndarray,
        start: jnp.ndarray,
        length: jnp.ndarray,
        final: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        rowwise_head: bool = True,
        all_logits: bool = False,
        depth_limit: Optional[int] = None,
    ) -> jnp.ndarray:
        """One RAGGED block step: a whole mixed prefill+decode serving
        iteration through the transformer in ONE program ("Ragged Paged
        Attention", PAPERS.md; ops/ragged_attention.py).

        tokens: (b, W) per-row token blocks padded to the fixed iteration
        width W — row b's valid tokens are columns [0, length[b]) at
        internal positions start[b] + j. A decode row carries 1 token (an
        image token at its decode position), a prefill-chunk row up to W
        REMAPPED text ids, an idle row nothing (length 0). Raggedness is
        DATA: every (start, length, final) mix shares this one trace, so
        a serving iteration is a single device dispatch with a single
        steady-state compile signature (serving/engine.py:_iteration_jit).

        ``final``: (b,) bool, True for rows whose sampled token the
        caller will CONSUME as a prefill's first image token (the
        final-chunk rows). It selects the head's accumulation shape, not
        its math: the split engine computes decode logits at batch width
        (an M=b gemm) but a prefill's first-token logits in a batch-1
        program whose M=1 head matvec accumulates ~1 ulp differently —
        so this step computes BOTH (the gemm head plus b per-row M=1
        heads) and selects per row, keeping fused output BITWISE equal
        to the split engine for every row kind (pinned by
        tests/test_ragged_attention). ``rowwise_head`` (STATIC) skips
        the per-row heads when the caller knows no row is final — the
        steady-state decode mix, where paying b extra head-weight matvec
        streams every iteration would erode the fusion's dispatch win;
        the engine passes ``bool(final.any())`` computed host-side, so
        this is one extra (warm, never in-trace) compile signature, not
        a per-mix recompile.

        Returns (b, num_image_tokens) image-only logits at each row's
        last valid position (garbage for idle/non-final intermediate
        rows — the engine discards them by kind). Requires the paged
        cache format and no gMLP layers, like every ragged-offset path.

        Speculative decoding (serving/engine.py) adds two STATIC knobs:

        ``all_logits`` returns (b, W, num_image_tokens) logits at EVERY
        block column — the k-token VERIFY head: a verify row's column j
        predicts position start + j + 1, so one ragged dispatch yields
        the target distribution for all k drafted positions. The head is
        one M=(b*W) gemm whose per-row results are bitwise equal to the
        M=b last-column gemm on the f32 parity tier (row-independent dot
        accumulation — the same cross-shape contract that makes
        fused == split); ``rowwise_head`` still overlays the per-row M=1
        head at final-chunk rows' last valid column, so a prefill
        completing inside a speculative iteration keeps split-path
        bit-parity for its first-token logits.

        ``depth_limit`` runs only the first L layers — the early-exit
        self-draft pass (the final norm + head apply to layer L's
        output). Draft quality is whatever the truncated stack gives;
        correctness never depends on it (exact acceptance re-derives
        every token from the full-depth verify logits).

        The block is ANCHORED at the descriptor ``start`` (attention
        write base, rotary/mask rows, shift-ring reads all derive from
        it rather than the stored cache indices), which is what lets a
        speculative rollback be pure descriptor arithmetic: a rejected
        suffix is simply overwritten by the next block dispatched at the
        accepted frontier. For non-speculative callers the stored
        indices equal ``start`` and the anchored arithmetic is
        value-identical.
        """
        b, n = tokens.shape
        assert "mlp" not in tuple(self.attn_types or ("full",)), (
            "fused_step cannot run gMLP layers (scalar-position gate history)"
        )
        pos = start[:, None] + jnp.arange(n, dtype=jnp.int32)[None]  # (b, n)
        is_text = pos < self.text_len_internal

        text_tok = jnp.clip(tokens, 0, self.num_text_tokens_ext - 1)
        img_tok = jnp.clip(tokens, 0, self.num_image_tokens - 1)
        emb = jnp.where(
            is_text[..., None], self.text_emb(text_tok), self.image_emb(img_tok)
        )
        if not self.rotary_emb:
            tpos = jnp.clip(pos, 0, self.text_len_internal - 1)
            ipos = jnp.clip(
                pos - self.text_len_internal, 0, self.image_seq_len - 1
            )
            img_grid = self.image_pos_emb(self.image_seq_len)
            pe = jnp.where(
                is_text[..., None],
                self.text_pos_emb(tpos),
                jnp.take(img_grid[0], ipos, axis=0),
            )
            emb = emb + pe.astype(emb.dtype)

        out = self.transformer(
            emb.astype(self.dtype),
            mask=self._full_key_mask(
                mask, self.text_len_internal + self.image_seq_len
            ),
            deterministic=True,
            decode=True,
            block_len=length,
            block_start=start,
            depth_limit=depth_limit,
        )
        last = jnp.clip(length - 1, 0, n - 1)
        h_last = jnp.take_along_axis(
            out, last[:, None, None], axis=1
        )  # (b, 1, dim)
        if all_logits:
            # the k-token verify head: logits at EVERY column, one
            # M=(b*W) gemm; final rows' last valid column is overlaid
            # with the per-row M=1 split-parity head below
            cols = self._head_image(out)  # (b, W, V_img)
            if rowwise_head:
                rowwise = jnp.concatenate(
                    [self._head_image(h_last[i:i + 1]) for i in range(b)],
                    axis=0,
                )[:, 0]  # per-row M=1 — the split prefill head
                sel = final[:, None] & (
                    jnp.arange(n, dtype=jnp.int32)[None] == last[:, None]
                )
                cols = jnp.where(sel[..., None], rowwise[:, None, :], cols)
            return cols
        batched = self._head_image(h_last)[:, 0]  # (b, V_img), M=b gemm
        if b == 1 or not rowwise_head:
            return batched
        rowwise = jnp.concatenate(
            [self._head_image(h_last[i:i + 1]) for i in range(b)], axis=0
        )[:, 0]  # per-row M=1 — the split prefill head's accumulation
        return jnp.where(final[:, None], rowwise, batched)

    def decode_step(
        self,
        token: jnp.ndarray,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        image_only: bool = False,
    ) -> jnp.ndarray:
        """One KV-cached decode step.

        token: (b,) id of the token at internal position ``pos`` — a remapped
        text id (bos included) when pos < text_len_internal, otherwise an
        un-offset image token id. Returns (b, total_tokens) logits predicting
        position pos+1. The transformer's cache collections must be mutable.
        The supplied K/V caches may be narrower than the full sequence (the
        segmented decode scan sizes them to the generation frontier,
        models/sampling.py) — every layer sweeps whatever extent it is
        handed (Attention._decode_attend).

        ``pos`` may be a SCALAR (the whole batch at one position — the
        decode scan) or a (b,) VECTOR of per-sequence positions (ragged
        decode offsets / continuous batching). The vector form requires a
        paged cache (per-sequence write indices, ops/attention.py); with
        learned positional tables (``rotary_emb=False``) the per-position
        embedding lookup becomes a row gather over the (b,) positions.

        ``image_only`` (static) asserts pos + 1 is an image position and
        computes only the image-vocab slice of the head, returning
        (b, num_image_tokens) logits — exactly the full head's ``[ext:]``
        slice, since image rows of the logits mask permit the whole image
        vocab (``logits_mask_np``). Measured on v5e int8 serving this is
        ~100 us/token: it removes the text-vocab head matvec columns AND
        the full-vocab (b, 18k) f32 mask/where/slice chain from the serial
        per-step op sequence.
        """
        b = token.shape[0]
        ragged = jnp.ndim(pos) == 1
        is_text = pos < self.text_len_internal

        text_tok = jnp.clip(token, 0, self.num_text_tokens_ext - 1)
        img_tok = jnp.clip(token, 0, self.num_image_tokens - 1)
        emb = jnp.where(
            is_text[:, None] if ragged else is_text,
            self.text_emb(text_tok), self.image_emb(img_tok),
        )
        if not self.rotary_emb:
            tpos = jnp.clip(pos, 0, self.text_len_internal - 1)
            ipos = jnp.clip(pos - self.text_len_internal, 0, self.image_seq_len - 1)
            img_grid = self.image_pos_emb(self.image_seq_len)
            if ragged:
                # per-sequence positions (continuous batching): the learned
                # tables become row gathers — (b,) indices -> (b, dim)
                pe = jnp.where(
                    is_text[:, None],
                    self.text_pos_emb(tpos),
                    jnp.take(img_grid[0], ipos, axis=0),
                )
            else:
                pe = jnp.where(
                    is_text,
                    self.text_pos_emb(tpos)[None],
                    jax.lax.dynamic_slice_in_dim(img_grid[0], ipos, 1, axis=0),
                )
            emb = emb + pe.astype(emb.dtype)

        x = emb[:, None, :].astype(self.dtype)
        out = self.transformer(
            x, mask=self._full_key_mask(mask, self.text_len_internal + self.image_seq_len),
            deterministic=True, decode=True,
        )
        if image_only:
            return self._head_image(out)[:, 0]
        logits = self._head(out)[:, 0]
        lm = jnp.asarray(self.logits_mask_np())
        p = jnp.minimum(pos, self.total_seq_len - 1)
        mask_row = lm[p] if ragged else jax.lax.dynamic_slice_in_dim(lm, p, 1, axis=0)
        return jnp.where(mask_row, NEG_INF, logits)
