"""Model reconstitution from checkpoints.

The reference snapshots model hparams inside every checkpoint so generation
needs no flag re-specification (train_dalle.py:514-517, generate.py:81-95).
Same contract here: the plain checkpoint carries ``meta`` with the model-class
name and constructor kwargs plus (for DALLE) the VAE class/params, and these
helpers rebuild modules + params from a path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax.numpy as jnp

from ..utils.checkpoint import load_checkpoint, save_checkpoint
from .dalle import DALLE
from .vae import DiscreteVAE

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def _config_dict(module) -> dict:
    """Constructor kwargs of a flax module (dataclass fields), with dtypes
    stringified for json."""
    out = {}
    for f in dataclasses.fields(module):
        if f.name in ("parent", "name"):
            continue
        v = getattr(module, f.name)
        if v in (jnp.float32, jnp.bfloat16, jnp.float16):
            v = jnp.dtype(v).name
        out[f.name] = v
    return out


def _restore_dtypes(cfg: dict) -> dict:
    cfg = dict(cfg)
    for k in ("dtype", "param_dtype"):
        if isinstance(cfg.get(k), str):
            cfg[k] = _DTYPES[cfg[k]]
    if "attn_types" in cfg and isinstance(cfg["attn_types"], list):
        cfg["attn_types"] = tuple(cfg["attn_types"])
    if "normalization" in cfg and isinstance(cfg["normalization"], list):
        cfg["normalization"] = tuple(tuple(x) for x in cfg["normalization"])
    if "shape" in cfg and isinstance(cfg["shape"], list):
        cfg["shape"] = tuple(cfg["shape"])
    return cfg


# ------------------------------------------------------------------- VAE


def save_vae_checkpoint(path: str, vae: DiscreteVAE, params: Any, extra: Optional[dict] = None):
    meta = {"model_class": "DiscreteVAE", "config": _config_dict(vae), **(extra or {})}
    save_checkpoint(path, {"params": params}, meta)


def vae_from_checkpoint(path: str) -> Tuple[DiscreteVAE, Any, dict]:
    state, meta = load_checkpoint(path)
    assert meta.get("model_class") == "DiscreteVAE", (
        f"not a DiscreteVAE checkpoint: {meta.get('model_class')}"
    )
    vae = DiscreteVAE(**_restore_dtypes(meta["config"]))
    params = vae.init(
        {"params": __import__("jax").random.key(0), "gumbel": __import__("jax").random.key(0)},
        jnp.zeros((1, vae.image_size, vae.image_size, vae.channels)),
    )["params"]
    from flax import serialization

    params = serialization.from_state_dict(params, state["params"])
    return vae, params, meta


# ------------------------------------------------------------------ DALLE


def save_dalle_checkpoint(
    path: str,
    dalle: DALLE,
    params: Any,
    vae: Optional[DiscreteVAE] = None,
    vae_params: Any = None,
    extra: Optional[dict] = None,
    opt_state: Any = None,
    step: Any = None,
):
    """Plain single-file DALLE checkpoint bundling the frozen VAE and (when
    given) the optimizer state — the reference's {hparams, vae_params, epoch,
    weights, opt_state, scheduler_state} layout (train_dalle.py:514-519)."""
    meta = {
        "model_class": "DALLE",
        "config": _config_dict(dalle),
        **(extra or {}),
    }
    state = {"params": params}
    if vae is not None:
        meta["vae_class"] = type(vae).__name__
        meta["vae_config"] = _config_dict(vae)
        state["vae_params"] = vae_params
    if opt_state is not None:
        state["opt_state"] = opt_state
        meta["has_opt_state"] = True
    if step is not None:
        state["step"] = step
    save_checkpoint(path, state, meta)


def restore_opt_state(path: str, target: Any) -> Optional[Any]:
    """Restore the optimizer state saved by ``save_dalle_checkpoint`` into
    ``target``'s structure (None when the checkpoint carries none), so resume
    keeps Adam moments instead of silently resetting them."""
    from flax import serialization

    state, meta = load_checkpoint(path)
    if not meta.get("has_opt_state"):
        return None
    return serialization.from_state_dict(target, state["opt_state"])


def dalle_from_checkpoint(path: str):
    """-> (dalle, params, vae, vae_params, meta); vae is None when the
    checkpoint carries no VAE."""
    import jax
    from flax import serialization

    state, meta = load_checkpoint(path)
    assert meta.get("model_class") == "DALLE", (
        f"not a DALLE checkpoint: {meta.get('model_class')}"
    )
    dalle = DALLE(**_restore_dtypes(meta["config"]))
    text = jnp.zeros((1, dalle.text_seq_len), jnp.int32)
    image = jnp.zeros((1, dalle.image_seq_len), jnp.int32)
    params = jax.eval_shape(lambda: dalle.init(jax.random.key(0), text, image))["params"]
    params = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), params)
    params = serialization.from_state_dict(params, state["params"])

    vae = vae_params = None
    if "vae_config" in meta:
        assert meta.get("vae_class") == "DiscreteVAE", meta.get("vae_class")
        vae = DiscreteVAE(**_restore_dtypes(meta["vae_config"]))
        vp = vae.init(
            {"params": jax.random.key(0), "gumbel": jax.random.key(0)},
            jnp.zeros((1, vae.image_size, vae.image_size, vae.channels)),
        )["params"]
        vae_params = serialization.from_state_dict(vp, state["vae_params"])
    return dalle, params, vae, vae_params, meta
