"""Model reconstitution from checkpoints.

The reference snapshots model hparams inside every checkpoint so generation
needs no flag re-specification (train_dalle.py:514-517, generate.py:81-95).
Same contract here: the plain checkpoint carries ``meta`` with the model-class
name and constructor kwargs plus (for DALLE) the VAE class/params, and these
helpers rebuild modules + params from a path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax.numpy as jnp

from ..utils.checkpoint import load_checkpoint, save_checkpoint
from .dalle import DALLE
from .pretrained import OpenAIDiscreteVAE
from .vae import DiscreteVAE

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def vae_classes() -> dict:
    """Name -> class for every VAE family a checkpoint may carry (the
    reference's generate.py:86-91 three-way switch)."""
    from .vqgan import VQGanVAE

    return {
        "DiscreteVAE": DiscreteVAE,
        "OpenAIDiscreteVAE": OpenAIDiscreteVAE,
        "VQGanVAE": VQGanVAE,
    }


def deep_merge(a: dict, b: dict) -> dict:
    """Recursive dict merge (b wins on leaves) — sub-path inits (encode-only
    / decode-only) can both contribute children to the same submodule."""
    out = dict(a)
    for k, v in b.items():
        out[k] = (
            deep_merge(out[k], v)
            if isinstance(v, dict) and isinstance(out.get(k), dict)
            else v
        )
    return out


def init_vae_params(vae) -> Any:
    """A zeroed param tree with the right structure for ``vae`` — the
    from_state_dict restore target. Trainable DiscreteVAE inits through
    __call__ (needs a gumbel key); frozen wrappers init their enc/dec paths
    via the method-based entry points."""
    import jax

    key = jax.random.key(0)
    if isinstance(vae, DiscreteVAE):
        img = jnp.zeros((1, vae.image_size, vae.image_size, vae.channels))
        shapes = jax.eval_shape(
            lambda: vae.init({"params": key, "gumbel": key}, img)
        )["params"]
    else:
        img = jnp.zeros((1, vae.image_size, vae.image_size, 3))
        seq = jnp.zeros((1, vae.image_seq_len), jnp.int32)
        shapes = deep_merge(
            jax.eval_shape(
                lambda: vae.init(key, img, method="get_codebook_indices")
            )["params"],
            jax.eval_shape(lambda: vae.init(key, seq, method="decode"))["params"],
        )
    return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def _config_dict(module) -> dict:
    """Constructor kwargs of a flax module (dataclass fields), with dtypes
    stringified for json."""
    out = {}
    for f in dataclasses.fields(module):
        if f.name in ("parent", "name"):
            continue
        v = getattr(module, f.name)
        if v in (jnp.float32, jnp.bfloat16, jnp.float16):
            v = jnp.dtype(v).name
        out[f.name] = v
    return out


def _restore_dtypes(cfg: dict) -> dict:
    cfg = dict(cfg)
    for k in ("dtype", "param_dtype"):
        if isinstance(cfg.get(k), str):
            cfg[k] = _DTYPES[cfg[k]]
    if "attn_types" in cfg and isinstance(cfg["attn_types"], list):
        cfg["attn_types"] = tuple(cfg["attn_types"])
    if "normalization" in cfg and isinstance(cfg["normalization"], list):
        cfg["normalization"] = tuple(tuple(x) for x in cfg["normalization"])
    if "shape" in cfg and isinstance(cfg["shape"], list):
        cfg["shape"] = tuple(cfg["shape"])
    return cfg


# ------------------------------------------------------------------- VAE


def save_vae_checkpoint(path: str, vae, params: Any, extra: Optional[dict] = None):
    meta = {
        "model_class": type(vae).__name__,
        "config": _config_dict(vae),
        **(extra or {}),
    }
    save_checkpoint(path, {"params": params}, meta)


def vae_from_checkpoint(path: str) -> Tuple[Any, Any, dict]:
    state, meta = load_checkpoint(path)
    classes = vae_classes()
    cls = classes.get(meta.get("model_class"))
    assert cls is not None, f"not a VAE checkpoint: {meta.get('model_class')}"
    vae = cls(**_restore_dtypes(meta["config"]))
    from flax import serialization

    params = serialization.from_state_dict(
        init_vae_params(vae), state["params"]
    )
    return vae, params, meta


# ------------------------------------------------------------------ DALLE


def save_dalle_checkpoint(
    path: str,
    dalle: DALLE,
    params: Any,
    vae: Optional[DiscreteVAE] = None,
    vae_params: Any = None,
    extra: Optional[dict] = None,
    opt_state: Any = None,
    step: Any = None,
):
    """Plain single-file DALLE checkpoint bundling the frozen VAE and (when
    given) the optimizer state — the reference's {hparams, vae_params, epoch,
    weights, opt_state, scheduler_state} layout (train_dalle.py:514-519)."""
    meta = {
        "model_class": "DALLE",
        "config": _config_dict(dalle),
        **(extra or {}),
    }
    state = {"params": params}
    if vae is not None:
        meta["vae_class"] = type(vae).__name__
        meta["vae_config"] = _config_dict(vae)
        if isinstance(vae, DiscreteVAE):
            state["vae_params"] = vae_params
        # frozen pretrained wrappers (OpenAI dVAE / VQGAN) are NOT bundled:
        # their weights are immutable public downloads, and re-serializing
        # ~100s of MB into every periodic checkpoint would dominate save
        # latency — the loader reconstitutes them from the weight cache
        # (reference does the same: generate.py:86-91 re-instantiates by
        # class and the weights come from ~/.cache)
    if opt_state is not None:
        state["opt_state"] = opt_state
        meta["has_opt_state"] = True
    if step is not None:
        state["step"] = step
    save_checkpoint(path, state, meta)


def restore_opt_state(path: str, target: Any) -> Optional[Any]:
    """Restore the optimizer state saved by ``save_dalle_checkpoint`` /
    ``save_clip_checkpoint`` into ``target``'s structure (None when the
    checkpoint carries none), so resume keeps Adam moments instead of
    silently resetting them."""
    from flax import serialization

    state, meta = load_checkpoint(path)
    if not meta.get("has_opt_state"):
        return None
    return serialization.from_state_dict(target, state["opt_state"])


def _restore_params(module, init_args: Tuple[Any, ...], state_params: Any) -> Any:
    """Shape-inferred zero tree for ``module.init(*init_args)`` filled from a
    checkpoint's params state dict — the one restore idiom shared by the
    DALLE and CLIP loaders."""
    import jax
    from flax import serialization

    shapes = jax.eval_shape(
        lambda: module.init(jax.random.key(0), *init_args)
    )["params"]
    zeros = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    return serialization.from_state_dict(zeros, state_params)


def dalle_from_checkpoint(path: str, vae_weight_paths: Optional[dict] = None):
    """-> (dalle, params, vae, vae_params, meta); vae is None when the
    checkpoint carries no VAE.

    Frozen pretrained VAEs (OpenAI dVAE / VQGAN) are stored by class+config
    only; their weights are reconstituted from the download cache, or from
    local files given in ``vae_weight_paths`` (keys: ``openai_enc_path``,
    ``openai_dec_path``, ``vqgan_config_path``, ``vqgan_model_path``)."""
    import jax
    from flax import serialization

    state, meta = load_checkpoint(path)
    assert meta.get("model_class") == "DALLE", (
        f"not a DALLE checkpoint: {meta.get('model_class')}"
    )
    dalle = DALLE(**_restore_dtypes(meta["config"]))
    text = jnp.zeros((1, dalle.text_seq_len), jnp.int32)
    image = jnp.zeros((1, dalle.image_seq_len), jnp.int32)
    params = _restore_params(dalle, (text, image), state["params"])

    vae = vae_params = None
    wp = vae_weight_paths or {}
    if "vae_config" in meta:
        vae_class = meta.get("vae_class")
        cls = vae_classes().get(vae_class)
        assert cls is not None, f"unknown VAE class {vae_class}"
        vae = cls(**_restore_dtypes(meta["vae_config"]))
        if "vae_params" in state:
            vae_params = serialization.from_state_dict(
                init_vae_params(vae), state["vae_params"]
            )
        elif vae_class == "OpenAIDiscreteVAE":
            from .pretrained import load_openai_vae

            vae, vae_params = load_openai_vae(
                wp.get("openai_enc_path"), wp.get("openai_dec_path"),
                dtype=vae.dtype,
            )
        elif vae_class == "VQGanVAE":
            from .vqgan import load_vqgan_vae

            vae, vae_params = load_vqgan_vae(
                wp.get("vqgan_config_path"), wp.get("vqgan_model_path"),
                dtype=vae.dtype,
            )
    return dalle, params, vae, vae_params, meta


# ------------------------------------------------------------------- CLIP


def save_clip_checkpoint(
    path: str,
    clip,
    params: Any,
    extra: Optional[dict] = None,
    opt_state: Any = None,
):
    """Hparams-carrying CLIP checkpoint (same shape as the DALLE format:
    {config, params[, opt_state]} so generation reranking needs no flags)."""
    meta = {
        "model_class": "CLIP",
        "config": _config_dict(clip),
        **(extra or {}),
    }
    state = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
        meta["has_opt_state"] = True
    save_checkpoint(path, state, meta)


def clip_from_checkpoint(path: str) -> Tuple[Any, Any, dict]:
    """(CLIP module, params, meta) from a save_clip_checkpoint file."""
    from .clip import CLIP

    state, meta = load_checkpoint(path)
    assert meta.get("model_class") == "CLIP", (
        f"not a CLIP checkpoint: {meta.get('model_class')}"
    )
    clip = CLIP(**_restore_dtypes(meta["config"]))
    text = jnp.zeros((1, clip.text_seq_len), jnp.int32)
    image = jnp.zeros(
        (1, clip.visual_image_size, clip.visual_image_size, clip.channels)
    )
    params = _restore_params(clip, (text, image), state["params"])
    return clip, params, meta
