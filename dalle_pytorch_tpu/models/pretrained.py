"""Pretrained-VAE wrappers: OpenAI discrete VAE, re-owned in flax.

Capability parity with the reference's ``OpenAIDiscreteVAE``
(vae.py:103-133): fixed props num_layers=3 / image_size=256 /
num_tokens=8192, ``map_pixels``/``unmap_pixels`` 0.1-eps remap
(vae.py:47-51), encode = argmax over encoder logits (vae.py:115-120),
decode = one-hot -> decoder -> sigmoid over the first 3 of 6 output
channels (vae.py:122-130), and ``__call__`` raising because the model is
frozen and inference-only (vae.py:132-133).

The reference unpickles OpenAI's published encoder/decoder nn.Modules
through the ``DALL-E`` pip package (vae.py:14,107-108). Here the graphs are
re-implemented as NHWC flax modules (channels-last keeps the MXU's 128-lane
axis on channels) and the published torch checkpoints are ingested by a
weight converter:

- ``load_torch_checkpoint`` reads a torch pickle *without* needing the
  original ``dall_e`` classes — a tolerant unpickler substitutes stand-ins
  for unimportable classes and the parameter tree is walked out of the
  reconstructed module graph;
- ``convert_openai_encoder`` / ``convert_openai_decoder`` map the torch
  state-dict names/layouts onto the flax param tree (OIHW -> HWIO).

Downloads follow the reference's rank-aware protocol (vae.py:53-94): only
the process-0 host fetches, everyone else waits for the cached file.
"""

from __future__ import annotations

import io
import math
import os
import pickle
import time
import types
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

Dtype = Any

OPENAI_VAE_ENCODER_URL = "https://cdn.openai.com/dall-e/encoder.pkl"
OPENAI_VAE_DECODER_URL = "https://cdn.openai.com/dall-e/decoder.pkl"

LOGIT_LAPLACE_EPS = 0.1


def map_pixels(x: jnp.ndarray) -> jnp.ndarray:
    """[0, 1] -> logit-laplace domain (reference vae.py:47-48)."""
    return (1 - 2 * LOGIT_LAPLACE_EPS) * x + LOGIT_LAPLACE_EPS


def unmap_pixels(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of map_pixels, clamped to [0, 1] (reference vae.py:50-51)."""
    return jnp.clip((x - LOGIT_LAPLACE_EPS) / (1 - 2 * LOGIT_LAPLACE_EPS), 0, 1)


# ---------------------------------------------------------------- flax graphs


class OAIConv(nn.Module):
    """The dVAE's conv: square kernel, (kw-1)//2 same-padding, params named
    ``w`` (HWIO here; the torch original stores OIHW) and ``b``."""

    n_out: int
    kw: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        n_in = x.shape[-1]
        w = self.param(
            "w",
            nn.initializers.normal(stddev=1 / math.sqrt(n_in * self.kw**2)),
            (self.kw, self.kw, n_in, self.n_out),
            self.param_dtype,
        )
        b = self.param("b", nn.initializers.zeros, (self.n_out,), self.param_dtype)
        pad = (self.kw - 1) // 2
        out = jax.lax.conv_general_dilated(
            x.astype(self.dtype),
            w.astype(self.dtype),
            window_strides=(1, 1),
            padding=[(pad, pad), (pad, pad)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return out + b.astype(out.dtype)


class OAIEncoderBlock(nn.Module):
    """Bottleneck residual block: id path (1x1 conv on channel change) +
    post_gain * (relu-conv3, relu-conv3, relu-conv3, relu-conv1)."""

    n_out: int
    n_layers: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        n_hid = self.n_out // 4
        post_gain = 1 / self.n_layers**2
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)

        identity = (
            x
            if x.shape[-1] == self.n_out
            else OAIConv(self.n_out, 1, name="id_path", **kw)(x)
        )
        h = OAIConv(n_hid, 3, name="res_conv_1", **kw)(nn.relu(x))
        h = OAIConv(n_hid, 3, name="res_conv_2", **kw)(nn.relu(h))
        h = OAIConv(n_hid, 3, name="res_conv_3", **kw)(nn.relu(h))
        h = OAIConv(self.n_out, 1, name="res_conv_4", **kw)(nn.relu(h))
        return identity + post_gain * h


class OAIDecoderBlock(nn.Module):
    """Mirror of the encoder block: (relu-conv1, relu-conv3, relu-conv3,
    relu-conv3) residual path."""

    n_out: int
    n_layers: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        n_hid = self.n_out // 4
        post_gain = 1 / self.n_layers**2
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)

        identity = (
            x
            if x.shape[-1] == self.n_out
            else OAIConv(self.n_out, 1, name="id_path", **kw)(x)
        )
        h = OAIConv(n_hid, 1, name="res_conv_1", **kw)(nn.relu(x))
        h = OAIConv(n_hid, 3, name="res_conv_2", **kw)(nn.relu(h))
        h = OAIConv(n_hid, 3, name="res_conv_3", **kw)(nn.relu(h))
        h = OAIConv(self.n_out, 3, name="res_conv_4", **kw)(nn.relu(h))
        return identity + post_gain * h


class OpenAIEncoder(nn.Module):
    """4 groups x n_blk_per_group bottleneck blocks with 2x2 maxpool between
    groups (3 pools -> f=8 downsample), 7x7 input conv, relu + 1x1 conv to
    vocab logits."""

    group_count: int = 4
    n_hid: int = 256
    n_blk_per_group: int = 2
    vocab_size: int = 8192
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        """x: (b, h, w, 3) in the map_pixels domain -> (b, f, f, vocab)."""
        n_layers = self.group_count * self.n_blk_per_group
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)

        x = OAIConv(self.n_hid, 7, name="input", **kw)(x)
        for g, mult in enumerate((1, 2, 4, 8), start=1):
            for i in range(self.n_blk_per_group):
                x = OAIEncoderBlock(
                    mult * self.n_hid, n_layers, name=f"group_{g}_block_{i + 1}", **kw
                )(x)
            if g < self.group_count:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        return OAIConv(self.vocab_size, 1, name="output_conv", **kw)(x)


class OpenAIDecoder(nn.Module):
    """Inverse: 1x1 input conv from one-hot, 4 groups with nearest 2x
    upsample between (3 upsamples), relu + 1x1 conv to 2*3 output stats."""

    group_count: int = 4
    n_init: int = 128
    n_hid: int = 256
    n_blk_per_group: int = 2
    output_channels: int = 3
    vocab_size: int = 8192
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z):
        """z: (b, f, f, vocab) one-hot -> (b, 8f, 8f, 2*output_channels)."""
        n_layers = self.group_count * self.n_blk_per_group
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)

        x = OAIConv(self.n_init, 1, name="input", **kw)(z)
        for g, mult in enumerate((8, 4, 2, 1), start=1):
            for i in range(self.n_blk_per_group):
                x = OAIDecoderBlock(
                    mult * self.n_hid, n_layers, name=f"group_{g}_block_{i + 1}", **kw
                )(x)
            if g < self.group_count:
                x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
        x = nn.relu(x)
        return OAIConv(2 * self.output_channels, 1, name="output_conv", **kw)(x)


class OpenAIDiscreteVAE(nn.Module):
    """Frozen pretrained dVAE with the DiscreteVAE duck-type surface
    (``get_codebook_indices`` / ``decode`` / ``fmap_size`` /
    ``image_seq_len`` / ``num_tokens``), reference vae.py:103-133.

    ``decode`` returns display-space [0, 1] pixels (``normalization`` is
    None), unlike the trainable DiscreteVAE whose decoder emits normalized
    space.
    """

    image_size: int = 256
    num_layers: int = 3
    num_tokens: int = 8192
    n_hid: int = 256
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    normalization = None  # decode output is already [0, 1]

    @property
    def fmap_size(self) -> int:
        return self.image_size // (2**self.num_layers)

    @property
    def image_seq_len(self) -> int:
        return self.fmap_size**2

    def setup(self):
        kw = dict(
            n_hid=self.n_hid,
            vocab_size=self.num_tokens,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        self.enc = OpenAIEncoder(**kw)
        self.dec = OpenAIDecoder(**kw)

    def get_codebook_indices(self, img: jnp.ndarray) -> jnp.ndarray:
        """img: (b, h, w, 3) in [0, 1] -> (b, f*f) int32 token ids
        (reference vae.py:115-120)."""
        logits = self.enc(map_pixels(img))
        b = logits.shape[0]
        return jnp.argmax(logits, axis=-1).reshape(b, -1).astype(jnp.int32)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        """Token ids (b, n) -> (b, H, W, 3) pixels in [0, 1]
        (reference vae.py:122-130)."""
        b, n = img_seq.shape
        f = int(math.isqrt(n))
        z = jax.nn.one_hot(img_seq, self.num_tokens, dtype=self.dtype)
        x_stats = self.dec(z.reshape(b, f, f, self.num_tokens)).astype(jnp.float32)
        return unmap_pixels(jax.nn.sigmoid(x_stats[..., : 3]))

    def __call__(self, img):
        raise NotImplementedError(
            "OpenAIDiscreteVAE is frozen and inference-only "
            "(reference vae.py:132-133)"
        )


# ------------------------------------------------------- torch-pickle ingest


class _StandIn:
    """Stand-in for classes the unpickler can't import (e.g. dall_e.*):
    accepts any construction protocol and keeps the pickled state."""

    def __init__(self, *args, **kwargs):
        pass

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self.__dict__["_pickled_state"] = state


class _TolerantUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        try:
            return super().find_class(module, name)
        except (ImportError, AttributeError):
            return type(name, (_StandIn,), {"__module__": module})


def _walk_module_tree(obj, prefix="") -> Dict[str, np.ndarray]:
    """Extract a flat {dotted_name: ndarray} state dict from a (possibly
    stand-in) unpickled nn.Module graph."""
    out: Dict[str, np.ndarray] = {}
    d = getattr(obj, "__dict__", None) or {}
    for coll in ("_parameters", "_buffers"):
        for k, v in (d.get(coll) or {}).items():
            if v is not None:
                out[prefix + k] = np.asarray(v.detach().cpu().numpy())
    for k, v in (d.get("_modules") or {}).items():
        if v is not None:
            out.update(_walk_module_tree(v, prefix + k + "."))
    return out


def load_torch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Torch pickle -> flat numpy state dict. Handles plain state-dict
    pickles and full-module pickles whose defining package (dall_e, taming)
    is not installed."""
    import torch

    shim = types.ModuleType("tolerant_pickle")
    shim.Unpickler = _TolerantUnpickler
    shim.load = lambda f, **kw: _TolerantUnpickler(f).load()
    shim.loads = lambda b, **kw: _TolerantUnpickler(io.BytesIO(b)).load()
    shim.dump = pickle.dump
    shim.dumps = pickle.dumps
    shim.HIGHEST_PROTOCOL = pickle.HIGHEST_PROTOCOL
    obj = torch.load(
        path, map_location="cpu", pickle_module=shim, weights_only=False
    )
    if isinstance(obj, dict):
        # plain state dict (possibly nested under a conventional key)
        for key in ("state_dict", "model", "sd"):
            if key in obj and isinstance(obj[key], dict):
                obj = obj[key]
                break
        return {
            k: np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)
            for k, v in obj.items()
            if hasattr(v, "detach") or isinstance(v, np.ndarray)
        }
    return _walk_module_tree(obj)


def _conv_to_hwio(w: np.ndarray) -> np.ndarray:
    """torch OIHW conv weight -> flax HWIO."""
    return np.transpose(w, (2, 3, 1, 0))


def _convert_openai(sd: Dict[str, np.ndarray], kind: str) -> Dict[str, Any]:
    """Flat torch state dict (keys like ``blocks.group_1.block_2.res_path.
    conv_3.w``) -> the flax param tree of OpenAIEncoder/OpenAIDecoder."""
    params: Dict[str, Any] = {}

    def put(path: tuple, leaf: str, value: np.ndarray):
        node = params
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node.setdefault(path[-1], {})[leaf] = jnp.asarray(value)

    for key, value in sd.items():
        parts = key.split(".")
        if parts[0] == "blocks":
            parts = parts[1:]
        leaf = parts[-1]
        if leaf not in ("w", "b"):
            continue
        value = _conv_to_hwio(value) if leaf == "w" and value.ndim == 4 else value
        if parts[0] == "input":
            put(("input",), leaf, value)
        elif parts[0] == "output":
            put(("output_conv",), leaf, value)
        elif parts[0].startswith("group_"):
            mod = f"{parts[0]}_{parts[1]}"  # group_g_block_i
            if parts[2] == "id_path":
                put((mod, "id_path"), leaf, value)
            elif parts[2] == "res_path":
                put((mod, f"res_{parts[3]}"), leaf, value)  # res_conv_i
            else:
                raise ValueError(f"unrecognized {kind} key: {key}")
        else:
            raise ValueError(f"unrecognized {kind} key: {key}")
    return params


def convert_openai_encoder(sd: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return _convert_openai(sd, "encoder")


def convert_openai_decoder(sd: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return _convert_openai(sd, "decoder")


# ----------------------------------------------------------------- download


def cache_dir() -> Path:
    return Path(
        os.environ.get("DALLE_TPU_CACHE", Path.home() / ".cache" / "dalle_tpu")
    )


def download(url: str, root: Optional[Path] = None, timeout: int = 600) -> Path:
    """Cached download with the reference's *per-host* coordination semantics
    (vae.py:53-94: the local-root rank fetches, same-host ranks wait). JAX
    runs one process per host, and caches are host-local disks, so every
    process fetches its own copy; concurrent same-host processes are safe
    because writes go through a pid-unique temp file + atomic rename, and
    late arrivals see the finished file and skip."""
    root = Path(root) if root is not None else cache_dir()
    root.mkdir(parents=True, exist_ok=True)
    target = root / url.split("/")[-1]
    if target.exists():
        return target

    import urllib.request

    tmp = target.with_suffix(f".tmp.{os.getpid()}")
    with urllib.request.urlopen(url, timeout=timeout) as r, open(tmp, "wb") as f:
        while True:
            chunk = r.read(1 << 20)
            if not chunk:
                break
            f.write(chunk)
    tmp.rename(target)
    return target


def load_openai_vae(
    enc_path: Optional[str] = None,
    dec_path: Optional[str] = None,
    dtype: Dtype = jnp.float32,
):
    """(OpenAIDiscreteVAE, params): download (or take local paths to) the
    published encoder/decoder pickles and convert them. The wrapper's param
    tree nests them under 'enc' / 'dec'."""
    enc_path = enc_path or str(download(OPENAI_VAE_ENCODER_URL))
    dec_path = dec_path or str(download(OPENAI_VAE_DECODER_URL))
    params = {
        "enc": convert_openai_encoder(load_torch_checkpoint(enc_path)),
        "dec": convert_openai_decoder(load_torch_checkpoint(dec_path)),
    }
    return OpenAIDiscreteVAE(dtype=dtype), params
