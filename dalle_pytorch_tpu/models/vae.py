"""Discrete VAE, TPU-native.

Re-owns the reference's Gumbel-softmax discrete VAE
(dalle_pytorch.py:60-225) as a flax module with explicit PRNG keys and
NHWC layout (the TPU-friendly conv layout — channels last keeps the MXU's
128-lane dimension on channels):

- conv encoder: ``num_layers`` stride-2 4x4 convs + ReLU, optional ResBlocks,
  1x1 conv to ``num_tokens`` logit channels;
- Gumbel-softmax relaxation (``jax.random.gumbel`` noise, temperature ``temp``,
  optional straight-through) over the codebook — the one-hot x codebook
  contraction is a single (b·h·w, num_tokens) x (num_tokens, d) matmul;
- conv-transpose decoder back to pixels;
- loss = recon (MSE or smooth-L1, dalle_pytorch.py:134,211) +
  ``kl_div_loss_weight`` x KL(q || uniform) with the reference's batchmean
  reduction (dalle_pytorch.py:213-220).

The reference mutates module state for temperature annealing; here ``temp`` is
a plain argument to ``__call__`` so the train step stays a pure function.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

Dtype = Any


def gumbel_softmax(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: float,
    hard: bool = False,
    axis: int = -1,
) -> jnp.ndarray:
    """Sample a relaxed one-hot from ``logits`` along ``axis``.

    ``hard=True`` gives the straight-through estimator: a true one-hot in the
    forward pass, the soft sample's gradient in the backward pass
    (reference uses F.gumbel_softmax, dalle_pytorch.py:202).
    """
    gumbels = jax.random.gumbel(key, logits.shape, dtype=jnp.float32)
    y_soft = jax.nn.softmax((logits.astype(jnp.float32) + gumbels) / temperature, axis=axis)
    if not hard:
        return y_soft.astype(logits.dtype)
    index = jnp.argmax(y_soft, axis=axis)
    y_hard = jax.nn.one_hot(index, logits.shape[axis], axis=axis, dtype=y_soft.dtype)
    return (y_hard + y_soft - jax.lax.stop_gradient(y_soft)).astype(logits.dtype)


def smooth_l1_loss(pred: jnp.ndarray, target: jnp.ndarray, beta: float = 1.0) -> jnp.ndarray:
    """Huber / smooth-L1 with torch's default beta=1, mean reduction."""
    diff = jnp.abs(pred - target)
    loss = jnp.where(diff < beta, 0.5 * diff**2 / beta, diff - 0.5 * beta)
    return loss.mean()


def denormalize(images, normalization=((0.5,) * 3, (0.5,) * 3)):
    """Invert ``DiscreteVAE.norm`` for display/save: the decoder emits pixels
    in normalized space (trained against ``norm(img)``), so saving them raw
    crushes the lower half of the range to black. x*std + mean, clipped to
    [0, 1]. The reference instead min-max stretches at save time via
    ``save_image(normalize=True)`` / ``make_grid(range=(-1, 1))``.
    Accepts numpy or jax arrays; returns the same family."""
    import numpy as np

    images = np.asarray(images)
    if normalization is not None:
        means, stds = (np.asarray(t, dtype=images.dtype) for t in normalization)
        images = images * stds + means
    return np.clip(images, 0.0, 1.0)


class ResBlock(nn.Module):
    """3x3 -> 3x3 -> 1x1 residual conv block (reference dalle_pytorch.py:60-72)."""

    chan: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.chan, (3, 3), padding=1, dtype=self.dtype, param_dtype=self.param_dtype)(x)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (3, 3), padding=1, dtype=self.dtype, param_dtype=self.param_dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (1, 1), dtype=self.dtype, param_dtype=self.param_dtype)(h)
        return h + x


class DiscreteVAE(nn.Module):
    """Trainable Gumbel-softmax discrete VAE over NHWC images in [0, 1].

    Capability parity with the reference's DiscreteVAE
    (dalle_pytorch.py:74-225); all stochasticity flows through explicit keys
    (``rngs={'gumbel': key}``).
    """

    image_size: int = 256
    num_tokens: int = 512
    codebook_dim: int = 512
    num_layers: int = 3
    num_resnet_blocks: int = 0
    hidden_dim: int = 64
    channels: int = 3
    smooth_l1_loss: bool = False
    temperature: float = 0.9
    straight_through: bool = False
    kl_div_loss_weight: float = 0.0
    normalization: Optional[Tuple[tuple, tuple]] = ((0.5,) * 3, (0.5,) * 3)
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @property
    def fmap_size(self) -> int:
        return self.image_size // (2**self.num_layers)

    @property
    def image_seq_len(self) -> int:
        return self.fmap_size**2

    def setup(self):
        assert math.log2(self.image_size).is_integer(), "image size must be a power of 2"
        assert self.num_layers >= 1, "number of layers must be >= 1"

        self.codebook = nn.Embed(
            self.num_tokens, self.codebook_dim, param_dtype=self.param_dtype
        )

        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        has_res = self.num_resnet_blocks > 0

        enc = []
        for _ in range(self.num_layers):
            enc.append(nn.Conv(self.hidden_dim, (4, 4), strides=2, padding=1, **kw))
        self.enc_res = [
            ResBlock(self.hidden_dim, **kw) for _ in range(self.num_resnet_blocks)
        ]
        self.enc_convs = enc
        self.enc_out = nn.Conv(self.num_tokens, (1, 1), **kw)

        # decoder: optional 1x1 projection + resblocks first, then upsampling
        if has_res:
            self.dec_in = nn.Conv(self.hidden_dim, (1, 1), **kw)
        self.dec_res = [
            ResBlock(self.hidden_dim, **kw) for _ in range(self.num_resnet_blocks)
        ]
        dec = []
        for _ in range(self.num_layers):
            dec.append(nn.ConvTranspose(self.hidden_dim, (4, 4), strides=(2, 2), padding="SAME", **kw))
        self.dec_convs = dec
        self.dec_out = nn.Conv(self.channels, (1, 1), **kw)

    # ------------------------------------------------------------------ parts

    def norm(self, images: jnp.ndarray) -> jnp.ndarray:
        """Channelwise normalization (reference dalle_pytorch.py:154-162)."""
        if self.normalization is None:
            return images
        means, stds = (jnp.asarray(t, dtype=images.dtype) for t in self.normalization)
        return (images - means) / stds

    def encode_logits(self, img: jnp.ndarray) -> jnp.ndarray:
        """img: (b, h, w, c) in [0, 1] -> (b, f, f, num_tokens) logits."""
        x = self.norm(img).astype(self.dtype)
        for conv in self.enc_convs:
            x = nn.relu(conv(x))
        for block in self.enc_res:
            x = block(x)
        return self.enc_out(x)

    def get_codebook_indices(self, img: jnp.ndarray) -> jnp.ndarray:
        """Hard-argmax token ids (b, f*f) — the no-grad encode used for DALL-E
        training (reference dalle_pytorch.py:164-169)."""
        logits = self.encode_logits(img)
        b = logits.shape[0]
        return jnp.argmax(logits, axis=-1).reshape(b, -1)

    def _decode_embeds(self, embeds: jnp.ndarray) -> jnp.ndarray:
        """(b, f, f, codebook_dim) codebook features -> (b, h, w, c) pixels."""
        x = embeds.astype(self.dtype)
        if self.num_resnet_blocks > 0:
            x = self.dec_in(x)
        for block in self.dec_res:
            x = block(x)
        for conv in self.dec_convs:
            x = nn.relu(conv(x))
        return self.dec_out(x)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        """Token ids (b, n) -> pixels (reference dalle_pytorch.py:171-181)."""
        b, n = img_seq.shape
        f = int(math.isqrt(n))
        embeds = self.codebook(img_seq).reshape(b, f, f, self.codebook_dim)
        return self._decode_embeds(embeds)

    # ---------------------------------------------------------------- forward

    def __call__(
        self,
        img: jnp.ndarray,
        return_loss: bool = False,
        return_recons: bool = False,
        return_logits: bool = False,
        temp: Optional[float] = None,
    ):
        assert img.shape[1] == self.image_size and img.shape[2] == self.image_size, (
            f"input must have the correct image size {self.image_size}"
        )
        logits = self.encode_logits(img)
        if return_logits:
            return logits

        temp = self.temperature if temp is None else temp
        key = self.make_rng("gumbel")
        soft_one_hot = gumbel_softmax(
            logits, key, temperature=temp, hard=self.straight_through
        )
        # (b, f, f, num_tokens) x (num_tokens, d) -> (b, f, f, d): one matmul
        sampled = jnp.einsum(
            "bhwn,nd->bhwd",
            soft_one_hot,
            self.codebook.embedding.astype(soft_one_hot.dtype),
        )
        out = self._decode_embeds(sampled)

        if not return_loss:
            return out

        target = self.norm(img).astype(jnp.float32)
        out_f32 = out.astype(jnp.float32)
        recon_loss = (
            smooth_l1_loss(out_f32, target)
            if self.smooth_l1_loss
            else jnp.mean((out_f32 - target) ** 2)
        )

        # KL(q || uniform). The reference calls torch kl_div with a shape-(1,)
        # input and reduction='batchmean' (dalle_pytorch.py:213-220), which
        # divides by input.size(0) == 1 — i.e. the total SUM, not a mean;
        # verified against torch and preserved here.
        log_qy = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        qy = jnp.exp(log_qy)
        log_uniform = -jnp.log(float(self.num_tokens))
        kl_div = jnp.sum(qy * (log_qy - log_uniform))

        loss = recon_loss + kl_div * self.kl_div_loss_weight
        if not return_recons:
            return loss
        return loss, out
