from .transformer import Transformer

__all__ = ["Transformer"]
