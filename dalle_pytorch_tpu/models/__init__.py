from .clip import CLIP, masked_mean
from .dalle import DALLE, top_k_filter
from .pretrained import OpenAIDiscreteVAE
from .sampling import (
    decode_tokens,
    generate_image_tokens,
    generate_images,
    generate_texts,
    init_decode_cache,
    insert_decode_cache,
    merge_decode_caches,
    set_decode_offsets,
)
from .transformer import Transformer
from .vae import DiscreteVAE, ResBlock, denormalize, gumbel_softmax, smooth_l1_loss
from .vqgan import VQGanVAE

__all__ = [
    "CLIP",
    "DALLE",
    "DiscreteVAE",
    "OpenAIDiscreteVAE",
    "ResBlock",
    "Transformer",
    "VQGanVAE",
    "denormalize",
    "decode_tokens",
    "generate_image_tokens",
    "generate_images",
    "generate_texts",
    "gumbel_softmax",
    "init_decode_cache",
    "insert_decode_cache",
    "masked_mean",
    "merge_decode_caches",
    "set_decode_offsets",
    "smooth_l1_loss",
    "top_k_filter",
]
