"""Attention family, TPU-native.

One module, ``PatternAttention``, implements every attention pattern the
reference spreads over four torch classes (attention.py:39-384): dense causal
("full"), axial row/column ("axial_row"/"axial_col"), convolution-like local
("conv_like"), and DeepSpeed-style block-sparse ("sparse"). Design:

- every pattern is *defined* by a static (L, L) may-attend mask built at model
  construction (ops/masks.py) — shape-static, jit-friendly, no dynamic padding;
- "full" and "sparse" run as one dense masked attention (MXU-sized einsums;
  a Pallas block-sparse kernel can slot under "sparse" without changing
  semantics);
- "axial_row"/"axial_col"/"conv_like" additionally have grouped
  FLOP-efficient paths (row/col batching, conv patches) that the tests verify
  against the dense-masked oracle;
- a KV-cached decode mode serves autoregressive sampling with O(L) work per
  token: the reference re-runs the full prefix per sampled token
  (dalle_pytorch.py:481-486); here each layer attends from the new token to
  its cache through the pattern's mask row.

Quirk preserved for parity: rotary embeddings are applied to q, k *and* v,
exactly as the reference does (attention.py:32-35,63-64).
"""

from __future__ import annotations

from typing import Any, Optional

import functools

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from . import masks as masks_lib
from .flash_attention import (
    StaticMask,
    StaticTable,
    flash_attention,
    fused_qkv_attention,
    fused_qkv_supported,
)
from .layers import stable_softmax
from .rotary import apply_rotary_emb


_FLASH_MASK_CACHE: dict = {}


def _cached_flash_mask(module: "PatternAttention", n: int) -> StaticMask:
    """One StaticMask per (pattern config, n), built exactly once. Keyed on
    the fields ``pattern_mask()`` reads — NOT the module itself: a bound
    flax module (inside apply, holding variables) is unhashable, so an
    lru_cache over the module works at trace time only for unbound calls
    and raises mid-apply."""
    key = (
        module.attn_type, module.seq_len, module.causal,
        module.image_fmap_size, module.kernel_size, module.dilation,
        module.block_size, module.num_random_blocks, module.layout_seed, n,
    )
    cached = _FLASH_MASK_CACHE.get(key)
    if cached is None:
        cached = _FLASH_MASK_CACHE[key] = StaticMask(
            module.pattern_mask()[:n, :n]
        )
    return cached


_BLOCK_LAYOUT_CACHE: dict = {}
_SP_PLAN_CACHE: dict = {}


def _pattern_key(module: "PatternAttention", n: int) -> tuple:
    """The hashable pattern-config key (the `_cached_flash_mask` rule:
    key on the fields ``pattern_mask()`` reads, never the bound module)."""
    return (
        module.attn_type, module.seq_len, module.causal,
        module.image_fmap_size, module.kernel_size, module.dilation,
        module.block_size, module.num_random_blocks, module.layout_seed, n,
    )


def _cached_block_layout(
    module: "PatternAttention", n: int, block: int
) -> "bs_lib.BlockLayout":
    """One compiled BlockLayout per (pattern config, n, block), built once:
    BlockLayout hashes by identity, so jit/custom_vjp retrace only when the
    layout genuinely changes."""
    from . import block_sparse_attention as bs_lib

    key = _pattern_key(module, n) + (block,)
    cached = _BLOCK_LAYOUT_CACHE.get(key)
    if cached is None:
        cached = _BLOCK_LAYOUT_CACHE[key] = bs_lib.compile_block_layout(
            module.pattern_mask()[:n, :n], block, block
        )
    return cached


def _sparse_block(n: int) -> int:
    """Kernel-eligible block edge for the pair-grid sparse kernel: lanes
    must be a multiple of 128 and per-step overhead dominates below it
    (the flash kernel's measured floor), so eligibility is simply n
    divisible by 128 with at least two blocks — the production seqs
    (1280/2048/4096) all qualify; everything else keeps the dense paths."""
    return 128 if n % 128 == 0 and n >= 256 else 0


def _sp_plan_block(n: int, sp: int) -> int:
    """Assignment granularity for the dual-balanced sp plan: the kernel
    edge when eligible, else the largest power-of-two divisor of n that
    still gives every chip a shot at >= 1 block (CPU test shapes)."""
    for b in (128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0 and n // b >= sp:
            return b
    return 1


def _cached_sp_plan(module: "PatternAttention", n: int, sp: int):
    """One dual-balanced SpPlan per (pattern config, n, sp)."""
    from . import block_sparse_attention as bs_lib

    block = _sp_plan_block(n, sp)
    key = _pattern_key(module, n) + (sp, block)
    cached = _SP_PLAN_CACHE.get(key)
    if cached is None:
        cached = _SP_PLAN_CACHE[key] = bs_lib.compile_sp_plan(
            _cached_block_layout(module, n, block), sp
        )
    return cached


@functools.lru_cache(maxsize=None)
def _cached_rot_slice(table: StaticTable, n: int) -> StaticTable:
    """Stable-identity [:n] slice of a static rotary table (the fused
    kernel hashes tables by id)."""
    return StaticTable(table.table[:n])


def _flash_block(n: int) -> int:
    """Largest usable flash block: per-grid-iteration overhead dominates the
    kernel at small blocks (measured 10x slower at 128 than 640 for seq
    1280), so prefer the biggest multiple-of-128 divisor of n. 128 also
    bounds the lse block's lane dimension (must divide by 128)."""
    for b in (1280, 1024, 640, 512, 384, 256, 128):
        if n % b == 0:
            return b
    return 0

Dtype = Any

NEG_INF = -0.7 * float(np.finfo(np.float32).max)


def lane_pack_enabled() -> bool:
    """Whether single-token decode sweeps may use the lane-packed
    formulation (``PatternAttention._cache_attend``). "auto" (default):
    TPU only — it was measured there (0.823 -> 0.813 ms/token, v5e int8)
    and its regrouped contraction is NOT bitwise equal to the plain gemm
    at every head count (h=16, d=64 measured ~5e-7 apart on CPU), while
    the CPU tier is where the fused-vs-split serving BIT-parity gates
    run (tests/test_ragged_attention.py, tools/serve_smoke.py): gating
    the pack off-TPU keeps every CPU decode path on the one shared gemm.
    ``DALLE_TPU_LANE_PACK=0|1`` forces either way (tests use 1 to
    exercise the packed math on CPU)."""
    from .kv_policy import tpu_auto_env

    return tpu_auto_env("DALLE_TPU_LANE_PACK")


def _softmax(scores: jnp.ndarray, stable: bool, axis: int = -1) -> jnp.ndarray:
    scores = scores.astype(jnp.float32)
    return (
        stable_softmax(scores, axis=axis) if stable
        else jax.nn.softmax(scores, axis=axis)
    )


def dense_attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    stable: bool = False,
) -> jnp.ndarray:
    """q, k, v: (..., n, d) with q pre-scaled. mask broadcastable to
    (..., n_q, n_k), True = attend. Softmax accumulates in f32."""
    scores = jnp.einsum("...id,...jd->...ij", q, k, preferred_element_type=jnp.float32)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    attn = _softmax(scores, stable)
    return jnp.einsum("...ij,...jd->...id", attn.astype(v.dtype), v)


def cache_block_attend(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    allowed: jnp.ndarray,
    stable: bool = False,
) -> jnp.ndarray:
    """Masked attention of an n-token query block against a W-row cache
    view: q (b, n, h, d) pre-scaled, k_cache/v_cache any
    (b, W, h*d)-reshapeable rank, ``allowed`` broadcastable to
    (b, 1, n, W). Scores accumulate in f32; masked lanes contribute
    exp(NEG_INF) = 0.

    This is THE multi-token decode-block building block: monolithic
    prefill (``DALLE.prefill_step``), CHUNKED prefill
    (``DALLE.prefill_chunk`` — each chunk attends the already-written
    paged-KV prefix, assembled by ``paged_kv.gather`` through the page
    table, plus its own in-chunk causal rows of the pattern mask), the
    fused ragged iteration (``ops/ragged_attention.py``'s reference
    path), and the n > 1 branch of every cache format all route here
    through ``PatternAttention._cache_attend``. One implementation means
    chunked and monolithic prefill share every einsum, which is what
    makes chunk-size-invariant BIT-parity achievable at all.

    Width-1 blocks are deliberately computed as width-2 gemms (q row
    duplicated, result sliced back): XLA lowers a genuine n == 1 block to
    a matvec whose accumulation differs from the n >= 2 gemm by ~1 ulp
    (CPU, measured 2026-08 and re-confirmed for this fix). The pad
    resolves that caveat IN THE ATTENTION CORE: per-row results here are
    bitwise invariant across every block width n >= 1 AND across batch
    widths (both verified on CPU, pinned by
    tests/test_ragged_attention.py), so the fused ragged path needs no
    1-token-tail special case — its rows are padded to the iteration
    width anyway. NOTE the split engine still merges 1-token final
    chunks (engine._next_chunk): a batch-1 width-1 block's
    PROJECTION/FFN matmuls run as M=1 matvecs with the same ~1-ulp
    accumulation drift, which this pad cannot reach — the residual
    caveat is pinned precisely in tests/test_ragged_attention.py. Cost
    of the pad: one duplicated query row on a path whose work is
    dominated by the W-row cache sweep."""
    b, n, h, d = q.shape
    W = k_cache.shape[1]
    if n == 1:
        out = cache_block_attend(
            jnp.concatenate((q, q), axis=1), k_cache, v_cache, allowed,
            stable,
        )
        return out[:, :1]
    scores = jnp.einsum(
        "bnhd,blhd->bhnl", q, k_cache.reshape(b, W, h, d),
        preferred_element_type=jnp.float32,
    )
    scores = jnp.where(allowed, scores, NEG_INF)
    attn = _softmax(scores, stable)
    return jnp.einsum(
        "bhnl,blhd->bnhd", attn.astype(v_cache.dtype),
        v_cache.reshape(b, W, h, d),
    )


class PatternAttention(nn.Module):
    """Multi-head attention with a static sparsity pattern.

    ``seq_len`` is the full internal sequence length L the pattern is defined
    over (text_len-with-bos + image_fmap_size**2 for DALL-E layers; the plain
    sequence length for CLIP's non-causal encoders). Callers may pass any
    static n <= L of leading positions.
    """

    dim: int
    seq_len: int
    attn_type: str = "full"
    causal: bool = True
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    stable: bool = False
    image_fmap_size: Optional[int] = None
    kernel_size: int = 5
    dilation: int = 1
    block_size: int = 16
    num_random_blocks: Optional[int] = None
    layout_seed: int = 0
    use_flash: bool = True
    sp_axis: Optional[str] = None
    quant: bool = False
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @property
    def text_len(self) -> int:
        assert self.image_fmap_size is not None
        return self.seq_len - self.image_fmap_size**2

    def pattern_mask(self) -> np.ndarray:
        """The static (L, L) may-attend matrix defining this layer."""
        if self.attn_type == "full":
            if not self.causal:
                return np.ones((self.seq_len, self.seq_len), dtype=bool)
            return masks_lib.causal_mask(self.seq_len)
        if self.attn_type in ("axial_row", "axial_col"):
            return masks_lib.axial_mask(
                self.text_len, self.image_fmap_size, axis=0 if self.attn_type == "axial_row" else 1
            )
        if self.attn_type == "conv_like":
            return masks_lib.conv_mask(
                self.text_len, self.image_fmap_size, self.kernel_size, self.dilation
            )
        if self.attn_type == "sparse":
            return masks_lib.block_sparse_mask(
                self.seq_len,
                block_size=self.block_size,
                text_seq_len=self.text_len - 1,
                num_random_blocks=self.num_random_blocks,
                causal=self.causal,
                seed=self.layout_seed,
            )
        raise ValueError(f'attention type "{self.attn_type}" is not valid')

    # ---------------------------------------------------------------- forward

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        rotary_pos_emb: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
        decode: bool = False,
        force_dense: bool = False,
        block_len: Optional[jnp.ndarray] = None,
        block_start: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        b, n, _ = x.shape
        h, d = self.heads, self.dim_head
        inner = h * d

        from .layers import serving_dense

        dense = lambda features, use_bias, name: serving_dense(
            self.quant, features, use_bias=use_bias, name=name,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )
        qkv = dense(inner * 3, False, "to_qkv")(x)

        # the rotary table may arrive as a StaticTable (the Transformer's
        # single source of truth): the fused kernel consumes it statically,
        # every other path materializes the SAME table here — the two can
        # never diverge
        rot_static = (
            rotary_pos_emb if isinstance(rotary_pos_emb, StaticTable) else None
        )
        if rot_static is not None:
            rotary_pos_emb = jnp.asarray(rot_static.table)

        if decode:
            from . import decode_attention as _dk

            if (
                _dk.FUSED_DECODE_ENABLED
                and n == 1
                and self.use_flash
                and self.attn_type == "full"
                and self.causal
                and _dk.fused_decode_supported(h, d)
                and self._cache_format(b) != "paged"
                and not self._has_windowed_cache()
            ):
                # OPT-IN fused decode kernel (ops/decode_attention.py):
                # measured SLOWER than the XLA op chain on v5e (see that
                # module's docstring), so off unless DALLE_TPU_FUSED_DECODE=1
                out = self._decode_attend_fused(qkv, mask, rotary_pos_emb)
            else:
                # multi-token prefill blocks and non-"full" patterns: the
                # unfused path, (b, n, h, d) end to end against the same
                # n-major caches the kernel aliases. ``block_len`` (b,)
                # marks a RAGGED block (the fused serving iteration): row
                # b's valid tokens are columns [0, block_len[b]) — K/V
                # writes are masked to them and the cache index advances
                # per row (ops/ragged_attention.py).
                q, k, v = (
                    t.reshape(b, n, h, d) for t in jnp.split(qkv, 3, axis=-1)
                )
                out = self._decode_attend(
                    q, k, v, mask, rotary_pos_emb, block_len=block_len,
                    block_start=block_start,
                )
                out = out.reshape(b, n, inner)
        else:
            from ..parallel.context import sp_extent

            use_sp = (
                not force_dense
                and not self.is_initializing()
                and sp_extent(self.sp_axis) > 1
            )
            # pair-grid block-sparse kernel (ops/block_sparse_attention.py):
            # the grid visits only live block pairs, so — unlike the packed
            # flash path below, whose affine index maps still DMA every
            # block — sparse patterns stop paying dense memory traffic.
            # Policy-gated (auto = TPU): the dense-mask paths stay the
            # fallback and the parity oracle.
            use_block_sparse = False
            if (
                not use_sp
                and not force_dense
                and self.attn_type != "full"
                and _sparse_block(n) > 0
            ):
                from .block_sparse_attention import (
                    ENGAGE_FRAC,
                    sparse_kernel_enabled,
                )

                if sparse_kernel_enabled():
                    # engage only when the COMPILED layout actually skips
                    # block pairs: a pattern whose live stride is finer
                    # than the 128-block edge (axial_col at fmap <= 128,
                    # the 16-block DeepSpeed-style random layout) visits
                    # every causal pair — the pair grid would pay kernel
                    # overhead for zero skipped FLOPs, so it declines and
                    # the dense/flash paths keep those patterns
                    layout = _cached_block_layout(self, n, _sparse_block(n))
                    use_block_sparse = (
                        layout.visited_block_frac <= ENGAGE_FRAC
                    )
            # packed single-block path: q/k/v head slices stream straight
            # out of the projection layout, rotary applied in-kernel — no
            # split/reshape/transpose/rotary sweeps through HBM. EVERY
            # pattern rides this kernel at flash-eligible shapes, with the
            # non-full patterns streaming their static mask as an in-kernel
            # operand — measured at the flagship shape (seq 1280, v5e), the
            # kernel's full-square compute beats any grouped formulation
            # that materializes scores in HBM (see the measurement note at
            # _pattern_attend below)
            if (
                not use_sp
                and not use_block_sparse
                and self.use_flash
                and not force_dense
                and _flash_block(n) == n
                and fused_qkv_supported(n, h, d)
                and (rotary_pos_emb is None or rot_static is not None)
            ):
                pattern = (
                    _cached_flash_mask(self, n)
                    if self.attn_type != "full" else None
                )
                rot = (
                    _cached_rot_slice(rot_static, n)
                    if rot_static is not None else None
                )
                out = fused_qkv_attention(
                    qkv,
                    None if mask is None else mask[:, :n],
                    h, d, rot, self.causal, pattern, d**-0.5,
                    jax.devices()[0].platform != "tpu",
                )
                out = dense(self.dim, True, "to_out")(out)
                return nn.Dropout(self.dropout)(out, deterministic=deterministic)

            q, k, v = (
                t.reshape(b, n, h, d).transpose(0, 2, 1, 3)
                for t in jnp.split(qkv, 3, axis=-1)
            )
            if rotary_pos_emb is not None:
                table = rotary_pos_emb[:n][None, None]  # (1, 1, n, rot)
                q, k, v = (apply_rotary_emb(table, t) for t in (q, k, v))

            if use_sp:
                out = self._sp_attend(q, k, v, mask, n)
            elif use_block_sparse:
                out = self._block_sparse_attend(q, k, v, n, mask)
            elif (
                self.use_flash
                and not force_dense
                and _flash_block(n) > 0
            ):
                out = self._flash_attend(q, k, v, n, mask)
            else:
                out = self._pattern_attend(
                    q * (d**-0.5), k, v, mask, force_dense=force_dense
                )

            out = out.transpose(0, 2, 1, 3).reshape(b, -1, inner)
        out = dense(self.dim, True, "to_out")(out)
        return nn.Dropout(self.dropout)(out, deterministic=deterministic)

    # ------------------------------------------------------------ flash path

    def _flash_attend(self, q, k, v, n: int, mask=None):
        """Fused Pallas kernel for any static pattern
        (ops/flash_attention.py): O(n·d) memory, per-block skip of masked-out
        regions. A runtime (b, n) key-padding mask streams through the kernel
        as a fourth operand — no dense (n, n) fallback. The non-causal full
        pattern is analytic (all blocks dense), so it carries no (n, n)
        pattern operand either. Falls back to interpret mode off-TPU so
        tests run anywhere."""
        block = _flash_block(n)
        pattern = None
        if self.attn_type != "full":
            pattern = _cached_flash_mask(self, n)
        return flash_attention(
            q, k, v,
            key_mask=None if mask is None else mask[:, :n],
            causal=self.causal,
            pattern_mask=pattern,
            sm_scale=self.dim_head**-0.5,
            block_q=block,
            block_k=block,
            interpret=jax.devices()[0].platform != "tpu",
        )

    # ----------------------------------------------------- block-sparse path

    def _block_sparse_attend(self, q, k, v, n: int, mask=None):
        """Pair-grid block-sparse kernel (ops/block_sparse_attention.py):
        the compiled BlockLayout's live pairs ARE the grid, so masked
        blocks cost neither DMA nor FLOPs — the path that makes the
        sparse patterns pay at seq >= 2048. Interpret mode off-TPU, where
        the CPU parity tier pins it allclose against the dense-mask
        reference per layout (tests/test_block_sparse.py)."""
        from .block_sparse_attention import block_sparse_attention

        layout = _cached_block_layout(self, n, _sparse_block(n))
        return block_sparse_attention(
            q, k, v, layout,
            key_mask=None if mask is None else mask[:, :n],
            sm_scale=self.dim_head**-0.5,
            interpret=jax.devices()[0].platform != "tpu",
        )

    # -------------------------------------------------- sequence parallelism

    def _sp_attend(self, q, k, v, mask, n: int):
        """Sequence-parallel attention over the ``sp_axis`` mesh axis:
        ring attention for the dense-causal pattern
        (ops/ring_attention.py), the DUAL-BALANCED block plan for the
        sparse patterns (ops/block_sparse_attention.py — q-blocks dealt to
        chips so both block and visited-pair counts are even; an axial
        pattern's heavy text rows no longer serialize the slowest chip),
        and Ulysses all-to-all for the non-causal full pattern (CLIP's
        encoders — uniform rows, nothing to balance). The surrounding
        network stays GSPMD-sharded; only this core runs under shard_map.
        The reference has no sequence parallelism at all (SURVEY.md §5.7)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.context import active_mesh, batch_axes
        from .ring_attention import ring_attention, ulysses_attend

        mesh = active_mesh()
        sp = int(mesh.shape[self.sp_axis])
        assert n % sp == 0, f"seq len {n} not divisible by sp={sp}"
        d = self.dim_head
        scale = d**-0.5

        batch = batch_axes(mesh)
        head = "tp" if "tp" in mesh.axis_names else None
        qspec = P(batch, head, self.sp_axis, None)
        mspec = P(batch, self.sp_axis)

        if self.attn_type == "full" and self.causal:

            def body(q, k, v, km=None):
                return ring_attention(
                    q, k, v, self.sp_axis, sp,
                    causal=True, sm_scale=scale, key_mask=km,
                )

        elif self.attn_type in ("axial_row", "axial_col", "conv_like", "sparse"):
            from .block_sparse_attention import (
                sp_block_sparse_attend,
                sparse_kernel_enabled,
            )

            plan = _cached_sp_plan(self, n, sp)
            # chip-local compute rides the pair kernel at kernel-eligible
            # shapes (the chip tables are traced operands selected by
            # axis_index inside the body); dense-mask jnp otherwise
            from .block_sparse_attention import ENGAGE_FRAC

            use_kernel = (
                plan.layout.block_q == _sparse_block(n) != 0
                and plan.rows_per_chip % 128 == 0
                and plan.layout.visited_block_frac <= ENGAGE_FRAC
                and sparse_kernel_enabled()
            )
            interp = jax.devices()[0].platform != "tpu"
            stable = self.stable
            sp_axis = self.sp_axis

            def body(q, k, v, km=None):
                return sp_block_sparse_attend(
                    q, k, v, plan, sp_axis, sp,
                    sm_scale=scale, key_mask=km,
                    use_kernel=use_kernel, interpret=interp, stable=stable,
                )

        else:

            def local_fn(q, k, v, km):
                return self._pattern_attend(q * scale, k, v, km)

            def body(q, k, v, km=None):
                return ulysses_attend(
                    q, k, v, self.sp_axis, sp, local_fn, key_mask=km
                )

        args = (q, k, v) if mask is None else (q, k, v, mask[:, :n])
        in_specs = (qspec,) * 3 + ((mspec,) if mask is not None else ())
        from .jax_compat import shard_map

        return shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=qspec,
            check_vma=False,
        )(*args)

    def _pattern_attend(self, q, k, v, mask, force_dense: bool = False):
        """Dispatch to this pattern's FLOP-efficient path (q pre-scaled).

        These grouped forms serve the non-flash shapes (CPU tests, decode
        mask rows, seqs not divisible by 128). At flash-eligible shapes the
        patterns ride the packed flash kernel instead — a measured decision
        (flagship shape: depth 12, seq 1280, batch 8, v5e, 2026-07, via
        bench.py --patterns):

          full / packed flash kernel     134 ms/step   (59% MFU baseline)
          sparse via flash pattern op    138 ms/step   (0.97x)
          axial_row grouped (this file)  171 ms/step   (0.79x)
          conv_like grouped, rolled      532 ms/step   (0.25x)

        After routing every pattern through the flash pattern operand, all
        four measure 136-137 ms (0.98x of full) at the flagship shape.

        The grouped forms compute 5-40x fewer score FLOPs yet LOSE: with
        attention only ~16% of the flagship step, their HBM-materialized
        score tensors (the image-queries x text-keys f32 block alone is
        537 MB/layer) cost more than the packed kernel's full-square MXU
        compute, which keeps scores in VMEM. A trace of the rolled conv
        path shows 51% loop-fusion + 17% layout-copy time — VPU/HBM work
        XLA cannot turn back into matmuls. Ceiling check: even a perfect
        axial kernel (~20% of full's score FLOPs, in-kernel) would save
        only ~17 ms of 134 (1.15x) — not worth a bespoke Pallas kernel
        next to the 0.97x the shared pattern path already delivers. The
        patterns' value at TPU flash shapes is memory (O(n*d)) and
        reference semantic parity, not speed; their compute win remains
        real where it always was — shapes where flash cannot run."""
        if not force_dense:
            if self.attn_type in ("axial_row", "axial_col"):
                return self._axial_attend(q, k, v, mask)
            if self.attn_type == "conv_like":
                # rematerialize the conv core in backward: its saved
                # activations (f32 text+window score tensors, ~220 MB/layer
                # at the flagship shape) pushed the 12-layer step past HBM
                # (19.5 G > 15.75 G, measured), while recomputing the rolls
                # and dots costs only O(f^2 ks^2 d) VPU work. No params or
                # RNG inside — a pure jax.checkpoint is safe.
                return jax.checkpoint(self._conv_attend)(q, k, v, mask)
        return self._dense_attend(q, k, v, mask)

    # ------------------------------------------------------------ dense paths

    def _key_mask(self, mask: Optional[jnp.ndarray], n: int) -> Optional[jnp.ndarray]:
        if mask is None:
            return None
        return mask[:, None, None, :n]  # (b, 1, 1, n)

    def _dense_attend(self, q, k, v, mask):
        n = q.shape[-2]
        allowed = jnp.asarray(self.pattern_mask()[:n, :n])[None, None]
        key_mask = self._key_mask(mask, n)
        if key_mask is not None:
            allowed = allowed & key_mask
        return dense_attend(q, k, v, allowed, self.stable)

    # ----------------------------------------------------------- axial path

    def _split_text_image(self, t, n):
        """Split (b, h, n, d) into text (static text_len) and image parts,
        padding the image part with zeros to the full grid."""
        f = self.image_fmap_size
        tl = self.text_len
        pad = self.seq_len - n
        text, img = t[..., :tl, :], t[..., tl:, :]
        if pad:
            img = jnp.pad(img, ((0, 0), (0, 0), (0, pad), (0, 0)))
        return text, img.reshape(*t.shape[:2], f, f, t.shape[-1])

    def _axial_attend(self, q, k, v, mask):
        """Grouped axial attention: image queries attend within their own row
        (axial_row) or column (axial_col) plus the whole text prefix; text is
        plain causal. FLOPs: O(f^3) instead of O(f^4) for image-image."""
        b, h, n, d = q.shape
        f, tl = self.image_fmap_size, self.text_len
        axis = 0 if self.attn_type == "axial_row" else 1

        (q_text, q_img), (k_text, k_img), (v_text, v_img) = (
            self._split_text_image(t, n) for t in (q, k, v)
        )
        if axis == 1:  # group by columns: transpose the grid
            q_img, k_img, v_img = (t.swapaxes(2, 3) for t in (q_img, k_img, v_img))

        # text part: causal over text
        tmask = masks_lib.causal_mask(tl)[None, None]
        key_mask = self._key_mask(mask, tl)
        tmask = tmask & key_mask if key_mask is not None else jnp.asarray(tmask)
        out_text = dense_attend(q_text, k_text, v_text, tmask, self.stable)

        # image part: within-line causal + full text
        dots_line = jnp.einsum("bhxid,bhxjd->bhxij", q_img, k_img, preferred_element_type=jnp.float32)
        dots_text = jnp.einsum("bhxid,bhjd->bhxij", q_img, k_text, preferred_element_type=jnp.float32)

        line_mask = jnp.asarray(masks_lib.causal_mask(f))[None, None, None]
        if mask is not None:
            img_mask = jnp.pad(mask[:, tl:], ((0, 0), (0, self.seq_len - mask.shape[1])))
            img_mask = img_mask.reshape(-1, f, f)
            if axis == 1:
                img_mask = img_mask.swapaxes(1, 2)
            # (b, 1, x, 1, j): key j of line x
            line_mask = line_mask & img_mask[:, None, :, None, :]
            dots_text = jnp.where(mask[:, None, None, None, :tl], dots_text, NEG_INF)
        dots_line = jnp.where(line_mask, dots_line, NEG_INF)

        dots = jnp.concatenate((dots_text, dots_line), axis=-1)
        attn = _softmax(dots, self.stable).astype(v.dtype)
        attn_text, attn_line = attn[..., :tl], attn[..., tl:]
        out_img = jnp.einsum("bhxij,bhxjd->bhxid", attn_line, v_img) + jnp.einsum(
            "bhxij,bhjd->bhxid", attn_text, v_text
        )

        if axis == 1:
            out_img = out_img.swapaxes(2, 3)
        out_img = out_img.reshape(b, h, f * f, d)[..., : n - tl, :]
        return jnp.concatenate((out_text, out_img), axis=2)

    # ------------------------------------------------------------- conv path

    def _conv_window_mask(self) -> np.ndarray:
        """(img_seq, ks*ks) static validity mask: window element j of query p
        is a real in-grid position with flat index <= p."""
        f, ks, dil = self.image_fmap_size, self.kernel_size, self.dilation
        pad = ((ks - 1) * dil + 1) // 2
        p = np.arange(f * f)
        r, c = p // f, p % f
        offs = (np.arange(ks) * dil) - pad
        rr = r[:, None, None] + offs[None, :, None]  # (p, ks, 1)
        cc = c[:, None, None] + offs[None, None, :]  # (p, 1, ks)
        rr, cc = np.broadcast_to(rr, (f * f, ks, ks)), np.broadcast_to(cc, (f * f, ks, ks))
        in_grid = (rr >= 0) & (rr < f) & (cc >= 0) & (cc < f)
        idx = rr * f + cc
        ok = in_grid & (idx <= p[:, None, None])
        return ok.reshape(f * f, ks * ks)

    def _conv_attend(self, q, k, v, mask):
        """Conv-like local attention via per-offset grid rolls — the TPU
        analog of the reference's F.unfold over k/v feature maps
        (attention.py:156-158), reformulated so no (b, h, f^2, ks^2, d)
        window tensor is ever materialized: at the flagship shape those
        patch tensors are 400 MB each and blew HBM (21.4 G > 15.75 G,
        measured). Score k of query p is q[p]·k[p + off_k], so each of the
        ks^2 window offsets is one 2-D roll of the k/v grids plus an
        elementwise-product reduction over d — peak extra memory is the
        (b, h, f^2, ks^2) score tensor (~13 MB) and one rolled grid
        (~17 MB) instead. Wrapped-around roll entries land exactly where
        ``_conv_window_mask`` already marks the window invalid (out-of-grid
        or acausal), so masking is unchanged. FLOPs for image-image:
        O(f^2 * ks^2 * d)."""
        b, h, n, d = q.shape
        f, tl, ks, dil = self.image_fmap_size, self.text_len, self.kernel_size, self.dilation
        pad = ((ks - 1) * dil + 1) // 2

        (q_text, q_img), (k_text, k_img), (v_text, v_img) = (
            self._split_text_image(t, n) for t in (q, k, v)
        )

        # text part
        tmask = masks_lib.causal_mask(tl)[None, None]
        key_mask = self._key_mask(mask, tl)
        tmask = tmask & key_mask if key_mask is not None else jnp.asarray(tmask)
        out_text = dense_attend(q_text, k_text, v_text, tmask, self.stable)

        # window offsets in grid coordinates, row-major over the ks x ks
        # kernel — the same ordering _conv_window_mask uses
        offs = [
            ((i * dil) - pad, (j * dil) - pad)
            for i in range(ks) for j in range(ks)
        ]

        def shifted(t, dy, dx):
            # align k/v position (r+dy, c+dx) with query position (r, c)
            return jnp.roll(t, shift=(-dy, -dx), axis=(2, 3))

        dots_win = jnp.stack(
            [
                jnp.einsum(
                    "bhrcd,bhrcd->bhrc", q_img, shifted(k_img, dy, dx),
                    preferred_element_type=jnp.float32,
                )
                for dy, dx in offs
            ],
            axis=-1,
        ).reshape(b, h, f * f, ks * ks)
        q_flat = q_img.reshape(b, h, f * f, d)
        dots_text = jnp.einsum(
            "bhpd,bhjd->bhpj", q_flat, k_text,
            preferred_element_type=jnp.float32,
        )

        win_mask = jnp.asarray(self._conv_window_mask())[None, None]
        if mask is not None:
            img_mask = jnp.pad(mask[:, tl:], ((0, 0), (0, self.seq_len - mask.shape[1])))
            img_mask = img_mask.reshape(-1, f, f)
            valid_k = jnp.stack(
                [
                    jnp.roll(img_mask, shift=(-dy, -dx), axis=(1, 2))
                    for dy, dx in offs
                ],
                axis=-1,
            ).reshape(-1, 1, f * f, ks * ks)  # (b, 1, p, ks*ks)
            win_mask = win_mask & valid_k
            dots_text = jnp.where(mask[:, None, None, :tl], dots_text, NEG_INF)
        dots_win = jnp.where(win_mask, dots_win, NEG_INF)

        dots = jnp.concatenate((dots_text, dots_win), axis=-1)
        attn = _softmax(dots, self.stable).astype(v.dtype)
        attn_text, attn_win = attn[..., :tl], attn[..., tl:]
        attn_grid = attn_win.reshape(b, h, f, f, ks * ks)
        out_img = jnp.einsum("bhpj,bhjd->bhpd", attn_text, v_text)
        out_img = out_img + sum(
            (attn_grid[..., idx, None] * shifted(v_img, dy, dx))
            for idx, (dy, dx) in enumerate(offs)
        ).reshape(b, h, f * f, d)
        out_img = out_img[..., : n - tl, :]
        return jnp.concatenate((out_text, out_img), axis=2)

    # ------------------------------------------------------------ decode path

    def _cache_format(self, b: int) -> str:
        """This decode call's cache layout format ("paged" | "flat" | "4d").

        A SUPPLIED cache's variables win (resized, merged, or replayed
        caches keep the format they were built with); with no cache yet,
        the layout policy decides (ops/kv_policy.py — the named, logged
        replacement for the inline ``b == 8`` magic branch that used to
        live here, with the full measured flat-vs-4-D history in its
        docstring)."""
        from . import kv_policy

        if self.has_variable("cache", "cached_key_pages"):
            return "paged"
        if self.has_variable("cache", "cached_key"):
            ck = self.get_variable("cache", "cached_key")
            return "flat" if ck.ndim == 3 else "4d"
        return kv_policy.choose_cache_format(b)

    def _decode_caches(self, b, dtype):
        """The flat/4-D decode cache variables — ONE declaration shared by
        the fused and unfused paths, so prefill (unfused) composes with
        fused per-token steps on bit-identical caches.

        The flat-vs-4-D rank is a measured, batch-conditional layout choice
        (v5e-1 int8 flagship, 2026-07): 4-D (b, L, h, d) compiles to a
        positions-minor layout whose one-row dynamic-update-slice rewrites
        the whole buffer (43% of the batch-8 decode program by trace);
        FLAT (b, L, h*d) fixes that exactly at batch 8 (4,870 -> 6,705
        tok/s) and loses at batches 1/4/16/32 on the same chip. The policy
        lives in ops/kv_policy.py (4-D at b=1, flat at b=8, paged pools —
        ``_paged_caches`` below — elsewhere); every sweep/update site here
        handles either rank, and DALLE_TPU_KV_FORMAT / DALLE_TPU_FLAT_KV
        override for re-measurement."""
        h, d, L = self.heads, self.dim_head, self.seq_len
        fmt = self._cache_format(b)
        assert fmt in ("flat", "4d"), (
            f"paged caches are declared by _paged_caches, not here ({fmt})"
        )
        kv_shape = (b, L, h * d) if fmt == "flat" else (b, L, h, d)
        is_init = not self.has_variable("cache", "cached_key")
        cached_key = self.variable(
            "cache", "cached_key", jnp.zeros, kv_shape, dtype
        )
        cached_value = self.variable(
            "cache", "cached_value", jnp.zeros, kv_shape, dtype
        )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.array(0, dtype=jnp.int32)
        )
        return cached_key, cached_value, cache_index, is_init

    def _decode_attend_fused(self, qkv, mask, rotary_pos_emb):
        """Single-token decode through the fused Pallas kernel
        (ops/decode_attention.py)."""
        from .decode_attention import fused_decode_attention
        from .rotary import _rotate_half_matrix

        b = qkv.shape[0]
        h, d = self.heads, self.dim_head
        L = self.seq_len

        cached_key, cached_value, cache_index, is_init = self._decode_caches(
            b, qkv.dtype
        )
        if is_init:
            return jnp.zeros((b, 1, h * d), qkv.dtype)

        idx = cache_index.value
        use_rotary = rotary_pos_emb is not None
        if use_rotary:
            # angles cast to the compute dtype before cos/sin, matching
            # apply_rotary_emb (ops/rotary.py:82); the kernel widens to f32
            ang = rotary_pos_emb.astype(qkv.dtype)
            cos, sin = jnp.cos(ang), jnp.sin(ang)
        else:
            cos = jnp.zeros((L, d), qkv.dtype)
            sin = cos
        rot_p = jnp.asarray(_rotate_half_matrix(d), qkv.dtype)
        key_mask = None if mask is None else mask[..., None].astype(jnp.int32)

        flat_kv = cached_key.value.ndim == 3
        out, k_row, v_row = fused_decode_attention(
            qkv,
            cached_key.value.reshape(b, L, h * d),
            cached_value.value.reshape(b, L, h * d),
            idx, cos, sin, rot_p, key_mask,
            heads=h, dim_head=d, use_rotary=use_rotary,
            interpret=jax.devices()[0].platform != "tpu",
        )
        upd = jax.lax.dynamic_update_slice_in_dim
        row_shape = (b, 1, h * d) if flat_kv else (b, 1, h, d)
        cached_key.value = upd(
            cached_key.value, k_row.reshape(row_shape), idx, axis=1
        )
        cached_value.value = upd(
            cached_value.value, v_row.reshape(row_shape), idx, axis=1
        )
        cache_index.value = idx + 1
        return out

    def _has_windowed_cache(self) -> bool:
        """True when a supplied decode cache is narrower than seq_len (the
        segmented decode scan, models/sampling.py, grows the cache arrays
        between scan segments so early tokens sweep a smaller buffer)."""
        if not self.has_variable("cache", "cached_key"):
            return False
        ck = self.get_variable("cache", "cached_key")
        return ck.shape[1] != self.seq_len

    def _decode_attend(self, q, k, v, mask, rotary_pos_emb, block_len=None,
                       block_start=None):
        """Decode against an n-major (b, W, h, d) K/V cache: single-token
        steps or multi-token prefill blocks (n > 1, e.g. the text prompt in
        one parallel pass). Each new token's row of the pattern mask selects
        which cached keys it sees, so attending against the full-length cache
        (zeros beyond the write index, always masked) matches sequential
        decode exactly. The cache keeps positions on the second-major axis so
        the per-token cache-wide QK^T / AV sweeps scan (W, h*d) rows in the
        projection's natural layout and decode needs no head transposes at
        all. (The sweeps themselves are latency-bound on the serial
        cache-update -> read dependency, not layout-bound: per-token cost
        measured identical to the (b, h, W, d) variant.)

        The sweep extent W is the SUPPLIED cache's row count, normally
        seq_len: the segmented decode scan (models/sampling.py) passes
        caches sized to the generation frontier (guaranteeing idx + n <= W)
        so early tokens pay O(W) HBM traffic instead of O(seq_len). Rows in
        [idx + n, W) are zeros under a False pattern-mask column, exactly
        like the full-length case, so the result is mathematically
        identical — masked lanes contribute exp(-inf) = 0 either way (~1 ulp
        summation-order drift where the narrower einsum chunks
        differently)."""
        b, n, h, d = q.shape
        if self._cache_format(b) == "paged":
            return self._decode_attend_paged(
                q, k, v, mask, rotary_pos_emb, block_len=block_len,
                block_start=block_start,
            )
        if block_len is not None or block_start is not None:
            raise ValueError(
                "ragged blocks (block_len/block_start) need the paged cache "
                "format: the flat/4d formats' scalar cache index cannot "
                "advance per row"
            )

        cached_key, cached_value, cache_index, is_init = self._decode_caches(
            b, k.dtype
        )
        if is_init:
            return jnp.zeros_like(q)
        W = cached_key.value.shape[1]

        idx = cache_index.value
        if rotary_pos_emb is not None:
            rows = jax.lax.dynamic_slice_in_dim(rotary_pos_emb, idx, n, axis=0)
            rows = rows[None, :, None, :]  # broadcast over (b, n, h, d)
            q, k, v = (apply_rotary_emb(rows, t) for t in (q, k, v))
        q = q * (d**-0.5)

        flat_kv = cached_key.value.ndim == 3
        cached_key.value = jax.lax.dynamic_update_slice_in_dim(
            cached_key.value, k.reshape(b, n, h * d) if flat_kv else k, idx, axis=1
        )
        cached_value.value = jax.lax.dynamic_update_slice_in_dim(
            cached_value.value, v.reshape(b, n, h * d) if flat_kv else v, idx, axis=1
        )
        cache_index.value = idx + n
        k_cache = cached_key.value
        v_cache = cached_value.value

        allowed = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(self.pattern_mask())[:, :W], idx, n, axis=0
        )[None, None]  # (1, 1, n, W)
        if mask is not None:
            allowed = allowed & mask[:, None, None, :W]
        return self._cache_attend(q, k_cache, v_cache, allowed)

    # ------------------------------------------------------- paged decode

    def _kv_quant(self) -> str:
        """This paged decode call's storage quantization ("none" |
        "int8"). A SUPPLIED cache's variables win — a cache carrying
        scale pools IS quantized, one without them is not, so resized /
        merged / replayed caches keep the format they were built with;
        with no cache yet, the quant policy decides
        (ops/kv_policy.py:choose_kv_quant — explicit ``kv_quant=``
        override context, then DALLE_TPU_KV_QUANT, then "none"). Paged
        format only: the flat/4d caches never consult this (their
        single-stream int8 experiment measured SLOWER — the note at the
        bottom of this file)."""
        from . import kv_policy

        if self.has_variable("cache", "cached_key_scale_pages"):
            return "int8"
        if self.has_variable("cache", "cached_key_pages"):
            return "none"
        return kv_policy.choose_kv_quant()

    def _paged_caches(self, b, dtype):
        """The block-paged decode cache variables (ops/paged_kv.py): K/V
        page pools (b, n_pages, page, h*d), a per-sequence page table, and
        a PER-SEQUENCE (b,) write index — the only cache format whose index
        can express ragged decode offsets across the batch (continuous
        batching). Page size comes from kv_policy.page_size().

        Under ``kv_quant="int8"`` (ops/kv_policy.py) the content pools
        store int8 and two PARALLEL scale pools (b, n_pages, page, h)
        f32 ride the same page tables — pool-shaped like the content
        (feat = heads), so every pool primitive (append/gather/
        copy_pages/copy_pages_across/reset_rows and the prefix-cache
        arena indirection) covers scales by construction. Returned
        scale variables are None when unquantized."""
        from . import kv_policy, paged_kv

        h, d, L = self.heads, self.dim_head, self.seq_len
        page = kv_policy.page_size()
        n_p = paged_kv.num_pages(L, page)
        quant = self._kv_quant()
        is_init = not self.has_variable("cache", "cached_key_pages")
        pool_dtype = jnp.int8 if quant == "int8" else dtype
        pool_shape = (b, n_p, page, h * d)
        k_pool = self.variable(
            "cache", "cached_key_pages", jnp.zeros, pool_shape, pool_dtype
        )
        v_pool = self.variable(
            "cache", "cached_value_pages", jnp.zeros, pool_shape, pool_dtype
        )
        k_scale = v_scale = None
        if quant == "int8":
            scale_shape = (b, n_p, page, h)
            k_scale = self.variable(
                "cache", "cached_key_scale_pages", jnp.zeros, scale_shape,
                paged_kv.SCALE_DTYPE,
            )
            v_scale = self.variable(
                "cache", "cached_value_scale_pages", jnp.zeros, scale_shape,
                paged_kv.SCALE_DTYPE,
            )
        table = self.variable("cache", "page_table", paged_kv.identity_table, b, n_p)
        cache_index = self.variable(
            "cache", "cache_index", jnp.zeros, (b,), jnp.int32
        )
        return k_pool, v_pool, k_scale, v_scale, table, cache_index, is_init

    def _decode_attend_paged(self, q, k, v, mask, rotary_pos_emb,
                             block_len=None, block_start=None):
        """Decode against the block-paged cache: rotary rows, pattern-mask
        rows, and the write position are all indexed PER SEQUENCE from the
        (b,) cache index, so a batch whose sequences sit at different
        decode offsets runs in one step (continuous batching — the
        flat/4-D scalar-index formats cannot express it). The per-step
        cache update is a one-row scatter inside one page per sequence;
        the gather then assembles the logical (b, W, h*d) view (W = pages
        * page_size, >= the frontier; rows past a sequence's own frontier
        are zeros under a False pattern-mask column, the same masked-zeros
        argument as the flat path). Attention arithmetic is the shared
        ``_cache_attend``, so paged/flat/4-D parity is exact by
        construction.

        ``block_len`` (b,) marks a RAGGED block — the fused serving
        iteration's descriptor (ops/ragged_attention.py): row b's valid
        tokens are columns [0, block_len[b]) of the padded width-n block.
        K/V writes are masked to the valid columns (``paged_kv.append``
        ``limit``), the cache index advances by block_len per row, and on
        TPU the attention core dispatches to the Pallas ragged
        paged-attention kernel for causal-"full" layers; everywhere else
        it stays the gathered-view ``_cache_attend`` — the SAME einsums
        as the split prefill-chunk/decode paths, which is what makes
        fused-vs-split engine parity bitwise on the f32 CPU tier. Invalid
        columns
        compute garbage that is finite (clipped mask rows keep at least
        one key visible) and discarded by the caller.

        ``block_start`` (b,), optional (requires ``block_len``): anchor the
        block at the DESCRIPTOR's position instead of the stored cache
        index — the speculative-decode rewind (serving/engine.py). A
        verify block writes its full padded length, but only
        ``accepted`` positions survive; the next descriptor's
        block_start lags the stored index by the rejected count, and
        anchoring the write base, rotary rows, and mask rows there makes
        the rejected positions plain overwrites: garbage K/V beyond the
        anchor frontier is causally masked until the next block lands on
        it. With block_start equal to the stored index (every
        non-speculative fused dispatch) the arithmetic is value-identical
        to the unanchored form."""
        from . import paged_kv, ragged_attention

        b, n, h, d = q.shape
        (k_pool, v_pool, k_scale, v_scale, table, cache_index,
         is_init) = self._paged_caches(b, k.dtype)
        if is_init:
            return jnp.zeros_like(q)

        if block_start is not None:
            assert block_len is not None, (
                "block_start anchoring is a ragged-block feature: pass "
                "block_len"
            )
            idx = block_start  # (b,) descriptor anchor
        else:
            idx = cache_index.value  # (b,)
        pos = idx[:, None] + jnp.arange(n, dtype=idx.dtype)[None]  # (b, n)
        if rotary_pos_emb is not None:
            T = rotary_pos_emb.shape[0]
            rows = jnp.take(rotary_pos_emb, jnp.minimum(pos, T - 1), axis=0)
            q, k, v = (
                apply_rotary_emb(rows[:, :, None, :], t) for t in (q, k, v)
            )
        q = q * (d**-0.5)

        hd = h * d
        k_rows, v_rows = k.reshape(b, n, hd), v.reshape(b, n, hd)
        if k_scale is not None:
            # int8 storage: quantize at APPEND time (per-row, per-head
            # symmetric scales — paged_kv.quantize_rows) and append the
            # scales to the parallel scale pools through the SAME table/
            # index/limit, so bytes and scales can never go out of step
            # (the spec-decode rewind overwrites both identically)
            k_rows, k_s = paged_kv.quantize_rows(k_rows, h)
            v_rows, v_s = paged_kv.quantize_rows(v_rows, h)
            k_scale.value = paged_kv.append(
                k_scale.value, table.value, idx, k_s, limit=block_len
            )
            v_scale.value = paged_kv.append(
                v_scale.value, table.value, idx, v_s, limit=block_len
            )
        k_pool.value = paged_kv.append(
            k_pool.value, table.value, idx, k_rows, limit=block_len,
        )
        v_pool.value = paged_kv.append(
            v_pool.value, table.value, idx, v_rows, limit=block_len,
        )
        if block_start is not None:
            # idle rows (block_len 0) carry garbage descriptors; their
            # stored index passes through untouched
            cache_index.value = jnp.where(
                block_len > 0, idx + block_len, cache_index.value
            )
        else:
            cache_index.value = idx + (n if block_len is None else block_len)

        causal_full = self.attn_type == "full" and self.causal
        if (
            block_len is not None
            and ragged_attention.use_kernel(causal_full, mask is not None)
        ):
            return ragged_attention.kernel_attend(
                q, k_pool.value, v_pool.value, table.value, idx, block_len,
                interpret=jax.devices()[0].platform != "tpu",
                k_scales=None if k_scale is None else k_scale.value,
                v_scales=None if v_scale is None else v_scale.value,
            )

        k_cache = paged_kv.gather(k_pool.value, table.value)  # (b, W, h*d)
        v_cache = paged_kv.gather(v_pool.value, table.value)
        if k_scale is not None:
            # read-time dequant of the gathered view: the ONE shared
            # formula (paged_kv.dequant) the Pallas kernel also
            # implements per page, widened back to the compute dtype
            # before the shared attention core
            k_cache = paged_kv.dequant(
                k_cache, paged_kv.gather(k_scale.value, table.value), k.dtype
            )
            v_cache = paged_kv.dequant(
                v_cache, paged_kv.gather(v_scale.value, table.value), v.dtype
            )
        W = k_cache.shape[1]

        pm = jnp.asarray(self.pattern_mask())  # (L, L)
        L = pm.shape[0]
        pm = pm[:, :W] if W <= L else jnp.pad(pm, ((0, 0), (0, W - L)))
        # per-sequence mask rows (jnp.take, clipped): row pos[b, j] of the
        # pattern selects which cached keys step j of sequence b sees
        allowed = jnp.take(pm, jnp.minimum(pos, L - 1), axis=0)  # (b, n, W)
        if mask is not None:
            km = mask[:, :W]
            if km.shape[1] < W:
                km = jnp.pad(km, ((0, 0), (0, W - km.shape[1])))
            allowed = allowed & km[:, None, :]
        return self._cache_attend(q, k_cache, v_cache, allowed[:, None])

    # -------------------------------------------- shared cache arithmetic

    def _cache_attend(self, q, k_cache, v_cache, allowed):
        """Masked attention of q (b, n, h, d — pre-scaled) against a cache
        view of W rows: k_cache/v_cache any (b, W, h*d)-reshapeable rank,
        ``allowed`` broadcastable to (b, 1, n, W). ONE implementation
        serves every cache format, so paged/flat/4-D can only differ in
        storage, never in arithmetic."""
        b, n, h, d = q.shape
        W = k_cache.shape[1]

        if (
            n == 1 and d < 128 and 128 % d == 0 and h % (128 // d) == 0
            and lane_pack_enabled()
        ):
            # lane-packed single-token sweeps: dim_head < 128 half-fills
            # the vector lanes of the (L, h, d) cache tiles, capping the
            # QK/AV sweeps at ~250 GB/s (trace-measured). Packing P=128/d
            # heads per 128-lane tile with a block-diagonal q restores
            # full-lane contractions — same math, better effective
            # bandwidth on the serving hot loop. TPU-gated
            # (lane_pack_enabled): the regrouped contraction is ~1-ulp
            # off the plain gemm at some head counts, and off-TPU the
            # fused-vs-split bit-parity gates need every decode on the
            # one shared gemm below.
            P_ = 128 // d
            G = h // P_
            eye = jnp.eye(P_, dtype=q.dtype)
            K2 = k_cache.reshape(b, W, G, P_ * d)
            V2 = v_cache.reshape(b, W, G, P_ * d)
            qr = q.reshape(b, G, P_, d)
            qblk = jnp.einsum("bgpd,pq->bgpdq", qr, eye).reshape(b, G, P_ * d, P_)
            s = jnp.einsum(
                "blgc,bgcp->bglp", K2, qblk, preferred_element_type=jnp.float32
            )
            # allowed (b|1, 1, 1, L) -> (b|1, 1, L, 1) over s's (b, g, l, p)
            s = jnp.where(allowed[:, :, 0, :, None], s, NEG_INF)
            att = _softmax(s, self.stable, axis=2)
            og = jnp.einsum(
                "bglp,blgc->bgpc", att.astype(V2.dtype), V2
            )  # (b, G, P, P*d); head p's output is its own 64-lane slice
            out = jnp.stack(
                [og[:, :, p, p * d:(p + 1) * d] for p in range(P_)], axis=2
            )
            return out.reshape(b, 1, h, d)

        return cache_block_attend(q, k_cache, v_cache, allowed, self.stable)

    # Decode cost accounting (int8 serving, v5e-1, measured by trace —
    # tools/analyze_trace.py, 2026-07): of ~0.82 ms/token, the int8 weight
    # matvecs take ~290 us (at/near HBM bandwidth — nothing left there),
    # the QK+AV cache sweeps ~244 us, small ops ~100 us, head+sampling the
    # rest. The sweeps ran at only ~250 GB/s because dim_head=64 half-fills
    # the 128-lane tiles of the (b, L, h, d) caches. The lane-packed XLA
    # reformulation in _cache_attend above (P heads per 128-lane tile,
    # block-diagonal q — same math, ~1 ulp off the plain gemm at some head
    # counts, hence TPU-gated via lane_pack_enabled) recovers part of that:
    # measured int8 0.823 -> 0.813 ms/token, bf16 1.044 -> 1.029
    # (reproduced twice).
    # The same packing done as a Pallas kernel (ops/decode_attention.py)
    # measured SLOWER than XLA's chain (skinny-MXU latency) and stays
    # opt-in; the residual sweep inefficiency is the remaining frontier.
    #
    # NOTE on int8 K/V caches (measured, v5e-1, 2026-07): quantizing the
    # decode caches was tried two ways — int8 storage widened inside the
    # cache dots (0.94 ms/token) and native s8xs8->s32 MXU dots with rowwise
    # scales on q/K/attn/V (1.44 ms/token) — and BOTH lost to the plain
    # bf16 cache (0.84 ms/token). Single-stream decode here is latency-bound
    # on the serial op chain, not HBM-bound: the ~31 MB/step the int8 cache
    # saves is worth ~40 us at HBM bandwidth, while the extra quantize /
    # dequantize elementwise stages add more serial work than that to every
    # one of the 1024 steps. The flat/4d caches therefore stay bf16; int8
    # serving quantizes what decode is actually bound on — the weight
    # matrices and embedding tables (utils/quantize.py). The PAGED serving
    # pools are the different regime that negative result does NOT cover:
    # the engine's batched pools are the largest HBM tenant of a
    # throughput-bound fleet (capacity, prefix-cache arena, and the
    # streamed-page kernel all scale with KV bytes), so they get an
    # opt-in int8 storage format with per-(token, head) scales behind
    # kv_policy.choose_kv_quant — see _paged_caches/_kv_quant above and
    # docs/DESIGN.md §6.1; TPU wall numbers pend a device session.
    #
    # Round-5 serial-chain attack (measured, v5e-1, 2026-07): the "head +
    # sampling the rest" slice of the accounting above was mostly NOT the
    # head matvec — it was the per-step (b, 18k)-wide f32 op chain around
    # it (logits-mask dynamic-slice + where, f32 cast, the [ext:] sampling
    # slice). The image-only head (models/dalle.py:_head_image) computes
    # just the image-vocab head columns and drops that chain entirely:
    # int8 batch-1 0.779 -> 0.686 ms/token. Two windowed-sweep designs were
    # then measured for the O(frontier)-instead-of-O(L) cache sweep idea:
    # (a) static sliced VIEWS of the full cache inside the step — XLA
    # materializes the slice as a per-step copy, +0.11 ms/token, REJECTED;
    # (b) frontier-sized cache ARRAYS grown between scan segments
    # (models/sampling.py:resize_kv) — batch-1 neutral-to-slightly-negative
    # (latency-bound), batch >= 8 wins 12-13% tokens/sec (sweep traffic
    # scales with batch). Hence the batch-adaptive segmentation default in
    # decode_tokens.
    #
    # Dynamic-update-slice traffic (trace-found, v5e-1, 2026-07): a batch-8
    # trace showed 43% of the decode program in DUS. Two separate causes,
    # two fixes: (1) the token-shift histories were full-sequence
    # (b, 1281, dim) buffers updated every step — but the shift only looks
    # back image_size positions, so they are now (image_size+1)-row rings
    # with static slice indices (ops/layers.py:PreShiftToken), worth ~3-4%
    # at batch 1 and ~40x less shift-cache memory; (2) the K/V caches'
    # 4-D shape compiled to a positions-minor layout whose one-row update
    # rewrites the whole buffer — see the measured batch-conditional
    # flat-vs-4-D policy in _decode_caches (batch 8: +38% tokens/sec).
