"""Fused (flash-style) attention Pallas TPU kernels.

The dense attention path materializes the (n, n) score matrix in HBM — at
DALL-E's seq 1280 that is the memory wall that caps batch size (and the
reference's DeepSpeed block-sparse CUDA kernel exists for the same reason,
attention.py:325-384). These kernels stream K/V blocks through VMEM with an
online-softmax accumulator, so activation memory is O(n·d) while the MXU sees
full (block_q x d x block_k) matmuls:

- forward: grid (b·h, n/bq, n/bk); the innermost k dimension iterates
  sequentially with running (max, denom, unnormalized out) in VMEM scratch;
  emits per-row logsumexp for the backward;
- backward: recompute-based (FlashAttention-2 decomposition, no stored
  probabilities): one kernel accumulates dq over k blocks — and computes
  delta = rowsum(do*o) in-kernel from blocks already in VMEM (no separate
  elementwise pass over do/o in HBM) — another accumulates (dk, dv) over
  q blocks, consuming the emitted delta;
- masking: ``causal=True`` is analytic (above-diagonal blocks execute no
  dots); an optional static (n, n) pattern mask (ops/masks.py) is streamed
  blockwise for sparse/axial/conv layouts with all-empty blocks skipped the
  same way; an optional runtime (b, n) key-padding mask (the reference's
  ``mask`` argument, attention.py:71-74) is a fourth streamed operand —
  (1, block_k) per grid step — folded into the scores after the static
  mask, so masked training/CLIP text padding keeps the O(n·d) memory
  guarantee instead of falling back to dense (n, n) scores. Rows whose
  every key is masked produce exactly 0 output and 0 gradient (the
  ``_masked_exp`` guard). This one kernel therefore covers the reference's
  dense causal attention, its pad-mask handling, and its DeepSpeed
  variable-sparsity kernel semantics.
  Skipped blocks still DMA their K/V block: index_maps must stay affine in
  the grid indices — an earlier revision routed them through the
  scalar-prefetch table to re-fetch the last live block, which defeats
  Mosaic's DMA pipelining and measured 23x slower at block 256 on v5e.

Parity is tested against the dense masked oracle (ops.attention.dense_attend)
in interpret mode on CPU and compiled on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .jax_compat import tpu_compiler_params

NEG_INF = -1e30
LANES = 128


class StaticMask:
    """Hashable wrapper for a static (n, n) bool may-attend mask, so it can
    ride through custom_vjp/jit static arguments without retracing (identity
    hash — build once per model, e.g. via a cached constructor)."""

    def __init__(self, mask):
        self.mask = np.asarray(mask, dtype=bool)

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


# --------------------------------------------------------------- static maps


def _block_visit_map(
    nq: int, nk: int, block_q: int, block_k: int,
    causal: bool, pattern_mask: Optional[np.ndarray],
) -> np.ndarray:
    """Static per-(qb, kb) class: 0 = skip, 1 = needs masking, 2 = dense."""
    visit = np.full((nq, nk), 2, dtype=np.int32)
    if pattern_mask is not None:
        for qb in range(nq):
            for kb in range(nk):
                blk = pattern_mask[
                    qb * block_q : (qb + 1) * block_q,
                    kb * block_k : (kb + 1) * block_k,
                ]
                visit[qb, kb] = 0 if not blk.any() else (2 if blk.all() else 1)
    elif causal:
        for qb in range(nq):
            for kb in range(nk):
                if kb * block_k > (qb + 1) * block_q - 1:
                    visit[qb, kb] = 0  # fully above the diagonal
                elif (kb + 1) * block_k - 1 > qb * block_q:
                    visit[qb, kb] = 1  # diagonal-crossing
    return visit


def _scalar_table(visit: np.ndarray) -> np.ndarray:
    """(1, nq*nk) int32 scalar-prefetch payload: the per-(outer, inner) visit
    class consumed by the kernel body to skip compute on dead blocks. (Index
    maps deliberately do NOT consult it — see the module docstring.)"""
    return visit.reshape(1, -1).astype(np.int32)


# ------------------------------------------------------------------ kernels


def _masked_scores(q, k, sm_scale, mask_ref, kmask_ref, visit, row0, col0, bq, bk):
    """(bq, bk) f32 scores with pattern/causal and runtime key masking
    applied. The QK^T dot runs in the inputs' dtype (bf16 on the MXU fast
    path) with f32 accumulation; the scale is applied on the f32 result.
    ``kmask_ref``: optional (1, 1, bk) int32 block of the runtime
    key-padding mask, broadcast over query rows."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if mask_ref is not None:
        # widen the int8 operand before comparing: Mosaic on v5e cannot
        # lower cmpi on the packed vector<..xi8> layout ("Target does not
        # support this comparison"); the i8->i32 convert is supported and
        # keeps the streamed mask at 1 byte/element
        s = jnp.where(mask_ref[:].astype(jnp.int32) > 0, s, NEG_INF)
    else:
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + row0
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + col0
        s = jnp.where(jnp.logical_or(visit == 2, rows >= cols), s, NEG_INF)
    if kmask_ref is not None:
        s = jnp.where(kmask_ref[0] > 0, s, NEG_INF)  # (1, bk) over rows
    return s


def _row_vec(ref):
    """(1, 1, bq) ref block -> (bq, 1) f32."""
    return jax.lax.transpose(ref[0], (1, 0))


def _masked_exp(s, x):
    """exp(s - x) with fully-masked entries forced to 0: rows masked in every
    visited block keep their running max / lse at NEG_INF, where exp(s - x)
    would be 1 — the guard enforces the 'fully-masked rows -> 0 output'
    contract (threshold is unreachable by real scores)."""
    return jnp.where(s > 0.5 * NEG_INF, jnp.exp(s - x), 0.0)


def _fwd_kernel(
    scalar_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, o_ref, lse_ref,
    m_scr, l_scr, acc_scr,
    *, sm_scale, block_q, block_k, nk,
):
    qb, kb = pl.program_id(1), pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    visit = scalar_ref[0, qb * nk + kb]

    @pl.when(visit > 0)
    def _():
        s = _masked_scores(
            q_ref[0], k_ref[0], sm_scale, mask_ref, kmask_ref, visit,
            qb * block_q, kb * block_k, block_q, block_k,
        )
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = _masked_exp(s, m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0:1] = l_scr[:, 0:1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:, 0:1] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(kb == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)  # (bq, 1)
        lse_ref[0] = jax.lax.transpose(lse, (1, 0))


def _bwd_dq_kernel(
    scalar_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, do_ref, o_ref, lse_ref,
    dq_ref, delta_ref, dq_scr, delta_scr,
    *, sm_scale, block_q, block_k, nk,
):
    qb, kb = pl.program_id(1), pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        # delta = rowsum(do * o), computed here from the blocks already in
        # VMEM instead of a separate elementwise pass over do/o in HBM; the
        # dkv kernel consumes the emitted delta_ref
        delta_scr[:, 0:1] = jnp.sum(
            do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True,
        )

    visit = scalar_ref[0, qb * nk + kb]

    @pl.when(visit > 0)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = _masked_scores(
            q, k, sm_scale, mask_ref, kmask_ref, visit,
            qb * block_q, kb * block_k, block_q, block_k,
        )
        p = _masked_exp(s, _row_vec(lse_ref))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_scr[:, 0:1]) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)
        delta_ref[0] = jax.lax.transpose(delta_scr[:, 0:1], (1, 0))


def _bwd_fused_kernel(
    scalar_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, do_ref, o_ref, lse_ref,
    dq_ref, dk_ref, dv_ref,
    *, sm_scale, block_q, block_k,
):
    """Single-block backward (nq == nk == 1): the whole row fits one grid
    step, so dq, dk and dv come out of ONE score recomputation — 5 block
    dots (s, dp, dq, dv, dk) instead of the split kernels' 7 (the dq and
    dkv passes each re-derive s). At the flagship seq-1280 whole-row block
    this is the production backward; the split kernels remain for tiled
    grids, where dq accumulates over the inner k dimension while dk/dv
    need the transposed iteration order. delta = rowsum(do*o) is computed
    in-register — never written to HBM at all."""
    visit = scalar_ref[0, 0]

    @pl.when(visit > 0)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = _masked_scores(
            q, k, sm_scale, mask_ref, kmask_ref, visit, 0, 0, block_q, block_k,
        )
        p = _masked_exp(s, _row_vec(lse_ref))
        dv_ref[0] = jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dq_ref[0] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dq_ref.dtype)
        dk_ref[0] = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dk_ref.dtype)

    @pl.when(visit == 0)
    def _():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])


def _bwd_dkv_kernel(
    scalar_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, sm_scale, block_q, block_k, nq,
):
    kb, qb = pl.program_id(1), pl.program_id(2)

    @pl.when(qb == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    visit = scalar_ref[0, kb * nq + qb]

    @pl.when(visit > 0)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = _masked_scores(
            q, k, sm_scale, mask_ref, kmask_ref, visit,
            qb * block_q, kb * block_k, block_q, block_k,
        )
        p = _masked_exp(s, _row_vec(lse_ref))
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - _row_vec(delta_ref)) * sm_scale).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qb == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ------------------------------------------------------------------ plumbing


def _prep(q, pattern_mask, block_q, block_k, causal):
    b, h, n, d = q.shape
    assert n % block_q == 0 and n % block_k == 0, (
        f"seq {n} must divide block sizes ({block_q}, {block_k})"
    )
    nq, nk = n // block_q, n // block_k
    mask_np = None
    if pattern_mask is not None:
        assert isinstance(pattern_mask, StaticMask), (
            "wrap the pattern mask in StaticMask (hashable static argument)"
        )
        mask_np = pattern_mask.mask
        assert mask_np.shape == (n, n), (mask_np.shape, n)
    visit = _block_visit_map(nq, nk, block_q, block_k, causal, mask_np)
    return b, h, n, d, nq, nk, mask_np, visit


def _kernel_cost(
    visit: np.ndarray, bh: int, block_q: int, block_k: int, d: int,
    dots_per_block: int, per_step_rows: int, per_outer_rows: int,
    dtype_bytes: int,
) -> pl.CostEstimate:
    """Cost of one pass over the live blocks — fed to XLA so compiled-module
    cost analysis and the scheduler see the kernel's real FLOPs instead of
    zero for the opaque custom call. ``dots_per_block``: dot_generals the
    body executes per live block (fwd 2: s, o-acc; dq 3: s, dp, dq;
    dkv 4: s, dv, dp, dk). Streamed-operand DMA happens on EVERY grid step
    (affine index maps — dead blocks skip compute, not traffic):
    ``per_step_rows`` rows of d move per inner step, ``per_outer_rows`` rows
    once per outer step (operands whose block index only depends on the
    outer grid dimension, plus outputs)."""
    live = int((visit > 0).sum())
    n_outer, n_inner = visit.shape
    per_dot = 2 * block_q * block_k * d
    return pl.CostEstimate(
        flops=bh * live * dots_per_block * per_dot,
        transcendentals=bh * live * block_q * block_k,  # exp
        bytes_accessed=bh
        * (n_outer * n_inner * per_step_rows + n_outer * per_outer_rows)
        * d
        * dtype_bytes,
    )


def _call(kernel, grid, in_specs, out_specs, out_shape, scratch, scalar, operands, interpret, cost=None):
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
        ),
        out_shape=out_shape,
        # batch*heads and outer blocks are independent; only the innermost
        # (accumulating) dimension is order-dependent — lets Mosaic pipeline
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        cost_estimate=cost,
        interpret=interpret,
    )(scalar, *operands)


def _with_optional_masks(kernel, has_mask, has_kmask, n_out, n_scratch):
    """Adapt a kernel with (mask_ref, kmask_ref) slots to calls missing
    either optional operand: the pattern mask and/or the runtime key mask."""

    def wrapped(*refs):
        split = len(refs) - n_out - n_scratch
        ins = list(refs[:split])
        rest = refs[split:]
        fixed, tail = ins[:4], ins[4:]  # scalar, q, k, v | optional + extras
        mask_ref = tail.pop(0) if has_mask else None
        kmask_ref = tail.pop(0) if has_kmask else None
        return kernel(*fixed, mask_ref, kmask_ref, *tail, *rest)

    return wrapped


def _bcast_key_mask(key_mask, b, h, n):
    """(b, n) bool key mask -> (b*h, 1, n) int32 streamed operand. The
    middle singleton keeps the block's sublane dimension equal to the
    array's (Mosaic requires block dims divisible by (8, 128) or equal to
    the array dims — the same layout trick as the lse operand). int32, not
    int8 like the pattern-mask operand: Mosaic on v5e cannot compare the
    packed vector<...xi8> layout this (1, 1, bk) block lowers to ("Target
    does not support this comparison"); the operand is (b·h, n) ints total,
    ~1/(2d) of one K operand, so the wider dtype is noise."""
    assert key_mask.shape == (b, n), (key_mask.shape, (b, n))
    return jnp.broadcast_to(
        key_mask[:, None, :].astype(jnp.int32), (b, h, n)
    ).reshape(b * h, 1, n)


def _flash_fwd(q, k, v, key_mask, causal, pattern_mask, sm_scale, block_q, block_k, interpret):
    b, h, n, d, nq, nk, mask_np, visit = _prep(q, pattern_mask, block_q, block_k, causal)
    scale = d**-0.5 if sm_scale is None else sm_scale
    bh = b * h
    qf, kf, vf = (t.reshape(bh, n, d) for t in (q, k, v))

    # index_maps under PrefetchScalarGridSpec receive the scalar-prefetch
    # ref as a trailing argument after the grid indices, but must stay affine
    # in the grid indices (module docstring)
    def kv_im(bhi, qb, kb, s):
        return (bhi, kb, 0)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, qb, kb, s: (bhi, qb, 0)),
        pl.BlockSpec((1, block_k, d), kv_im),
        pl.BlockSpec((1, block_k, d), kv_im),
    ]
    operands = [qf, kf, vf]
    if mask_np is not None:
        in_specs.append(
            pl.BlockSpec((block_q, block_k), lambda bhi, qb, kb, s: (qb, kb))
        )
        operands.append(jnp.asarray(mask_np, jnp.int8))
    if key_mask is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda bhi, qb, kb, s: (bhi, 0, kb))
        )
        operands.append(_bcast_key_mask(key_mask, b, h, n))

    kernel = _with_optional_masks(
        functools.partial(
            _fwd_kernel, sm_scale=scale, block_q=block_q, block_k=block_k, nk=nk
        ),
        mask_np is not None,
        key_mask is not None,
        n_out=2,
        n_scratch=3,
    )
    o, lse = _call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qb, kb, s: (bhi, qb, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bhi, qb, kb, s: (bhi, 0, qb)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n), jnp.float32),
        ],
        scratch=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        scalar=jnp.asarray(_scalar_table(visit)),
        operands=operands,
        interpret=interpret,
        cost=_kernel_cost(visit, bh, block_q, block_k, d, 2,
                          2 * block_k, 2 * block_q, q.dtype.itemsize),
    )
    return o.reshape(b, h, n, d), lse.reshape(b, h, n)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def flash_attention(
    q, k, v,
    key_mask=None,
    causal: bool = True,
    pattern_mask=None,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Fused attention over (b, h, n, d); q is NOT pre-scaled (``sm_scale``
    defaults to d**-0.5). ``pattern_mask``: static (n, n) bool array,
    True = may attend; hash by id, so build it once at model setup.
    ``key_mask``: runtime (b, n) bool array, True = key is attendable
    (the reference's pad mask, attention.py:71-74); rows with every key
    masked return exactly 0."""
    o, _ = _flash_fwd(q, k, v, key_mask, causal, pattern_mask, sm_scale, block_q, block_k, interpret)
    return o


def _fwd_rule(q, k, v, key_mask, causal, pattern_mask, sm_scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, key_mask, causal, pattern_mask, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, key_mask, o, lse)


def _bwd_rule(causal, pattern_mask, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, key_mask, o, lse = res
    b, h, n, d, nq, nk, mask_np, visit = _prep(q, pattern_mask, block_q, block_k, causal)
    scale = d**-0.5 if sm_scale is None else sm_scale
    bh = b * h

    qf, kf, vf, dof, of = (t.reshape(bh, n, d) for t in (q, k, v, do, o))
    lsef = lse.reshape(bh, 1, n)
    mask_op = [] if mask_np is None else [jnp.asarray(mask_np, jnp.int8)]
    km_op = [] if key_mask is None else [_bcast_key_mask(key_mask, b, h, n)]

    # ---- single-block fast path: one fused kernel, 5 dots instead of 7 ----
    if nq == 1 and nk == 1:
        def whole(bhi, qb, kb, s):
            return (bhi, 0, 0)

        row = whole

        fused_specs = [
            pl.BlockSpec((1, block_q, d), whole),
            pl.BlockSpec((1, block_k, d), whole),
            pl.BlockSpec((1, block_k, d), whole),
            *(
                [pl.BlockSpec((block_q, block_k), lambda bhi, qb, kb, s: (0, 0))]
                if mask_np is not None else []
            ),
            *(
                [pl.BlockSpec((1, 1, block_k), row)]
                if key_mask is not None else []
            ),
            pl.BlockSpec((1, block_q, d), whole),
            pl.BlockSpec((1, block_q, d), whole),
            pl.BlockSpec((1, 1, block_q), row),
        ]
        fused_kernel = _with_optional_masks(
            functools.partial(
                _bwd_fused_kernel, sm_scale=scale,
                block_q=block_q, block_k=block_k,
            ),
            mask_np is not None,
            key_mask is not None,
            n_out=3,
            n_scratch=0,
        )
        dq, dk, dv = _call(
            fused_kernel,
            grid=(bh, 1, 1),
            in_specs=fused_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), whole),
                pl.BlockSpec((1, block_k, d), whole),
                pl.BlockSpec((1, block_k, d), whole),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, n, d), q.dtype),
                jax.ShapeDtypeStruct((bh, n, d), q.dtype),
                jax.ShapeDtypeStruct((bh, n, d), q.dtype),
            ],
            scratch=[],
            scalar=jnp.asarray(_scalar_table(visit)),
            operands=[qf, kf, vf, *mask_op, *km_op, dof, of, lsef],
            interpret=interpret,
            cost=_kernel_cost(visit, bh, block_q, block_k, d, 5,
                              0, 7 * block_q, q.dtype.itemsize),
        )
        dkm = (
            None if key_mask is None
            else np.zeros(key_mask.shape, jax.dtypes.float0)
        )
        return (
            dq.reshape(b, h, n, d),
            dk.reshape(b, h, n, d),
            dv.reshape(b, h, n, d),
            dkm,
        )

    # ---- dq over k blocks (also emits delta = rowsum(do*o) for dkv) -------
    def kv_im(bhi, qb, kb, s):
        return (bhi, kb, 0)

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, qb, kb, s: (bhi, qb, 0)),
        pl.BlockSpec((1, block_k, d), kv_im),
        pl.BlockSpec((1, block_k, d), kv_im),
        *(
            [pl.BlockSpec((block_q, block_k), lambda bhi, qb, kb, s: (qb, kb))]
            if mask_np is not None else []
        ),
        *(
            [pl.BlockSpec((1, 1, block_k), lambda bhi, qb, kb, s: (bhi, 0, kb))]
            if key_mask is not None else []
        ),
        pl.BlockSpec((1, block_q, d), lambda bhi, qb, kb, s: (bhi, qb, 0)),
        pl.BlockSpec((1, block_q, d), lambda bhi, qb, kb, s: (bhi, qb, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bhi, qb, kb, s: (bhi, 0, qb)),
    ]
    dq_kernel = _with_optional_masks(
        functools.partial(
            _bwd_dq_kernel, sm_scale=scale, block_q=block_q, block_k=block_k, nk=nk
        ),
        mask_np is not None,
        key_mask is not None,
        n_out=2,
        n_scratch=2,
    )
    dq, deltaf = _call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=dq_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qb, kb, s: (bhi, qb, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bhi, qb, kb, s: (bhi, 0, qb)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n), jnp.float32),
        ],
        scratch=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        scalar=jnp.asarray(_scalar_table(visit)),
        operands=[qf, kf, vf, *mask_op, *km_op, dof, of, lsef],
        interpret=interpret,
        cost=_kernel_cost(visit, bh, block_q, block_k, d, 3,
                          2 * block_k, 4 * block_q, q.dtype.itemsize),
    )

    # ---- dk/dv over q blocks ----------------------------------------------
    visit_t = np.ascontiguousarray(visit.T)

    def q_im(bhi, kb, qb, s):
        return (bhi, qb, 0)

    def row_im(bhi, kb, qb, s):
        return (bhi, 0, qb)

    dkv_specs = [
        pl.BlockSpec((1, block_q, d), q_im),
        pl.BlockSpec((1, block_k, d), lambda bhi, kb, qb, s: (bhi, kb, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, kb, qb, s: (bhi, kb, 0)),
        *(
            [pl.BlockSpec((block_q, block_k), lambda bhi, kb, qb, s: (qb, kb))]
            if mask_np is not None else []
        ),
        *(
            [pl.BlockSpec((1, 1, block_k), lambda bhi, kb, qb, s: (bhi, 0, kb))]
            if key_mask is not None else []
        ),
        pl.BlockSpec((1, block_q, d), q_im),
        pl.BlockSpec((1, 1, block_q), row_im),
        pl.BlockSpec((1, 1, block_q), row_im),
    ]
    dkv_kernel = _with_optional_masks(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=scale, block_q=block_q, block_k=block_k, nq=nq
        ),
        mask_np is not None,
        key_mask is not None,
        n_out=2,
        n_scratch=2,
    )
    dk, dv = _call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bhi, kb, qb, s: (bhi, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, kb, qb, s: (bhi, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, d), q.dtype),
            jax.ShapeDtypeStruct((bh, n, d), q.dtype),
        ],
        scratch=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        scalar=jnp.asarray(_scalar_table(visit_t)),
        operands=[qf, kf, vf, *mask_op, *km_op, dof, lsef, deltaf],
        interpret=interpret,
        cost=_kernel_cost(visit_t, bh, block_q, block_k, d, 4,
                          2 * block_q, 4 * block_k, q.dtype.itemsize),
    )
    dkm = None if key_mask is None else np.zeros(key_mask.shape, jax.dtypes.float0)
    return (
        dq.reshape(b, h, n, d),
        dk.reshape(b, h, n, d),
        dv.reshape(b, h, n, d),
        dkm,
    )


flash_attention.defvjp(_fwd_rule, _bwd_rule)


# ===================================================================== fused
# Packed-qkv single-block path: consumes the attention projection's raw
# (b, n, 3*h*d) output directly and emits (b, n, h*d), with the DALL-E
# rotary rotation applied INSIDE the kernel. This deletes, per layer and
# per direction, the qkv split, three (b, n, h, d) reshapes, three
# (0, 2, 1, 3) transposes and three rotary HBM sweeps (measured ~8 ms/step
# at the flagship config) — the kernel reads head slices straight out of
# the projection layout. Mosaic requires a block's minor dim to be a
# multiple of 128, so the grid processes ceil(128/d) heads per step
# (2 for the flagship d=64), statically unrolled in the kernel body.
# Single-block only (n == block): the production dispatch for seq <= 1280;
# tiled grids keep the per-head kernels above.


class StaticTable:
    """Hashable id-wrapper for a static (n, rot_width) numpy angle table.
    Registered as an EMPTY pytree (all data in the static aux): one object
    serves as the single source of truth for rotary angles on every path —
    it rides through traced kwargs (remat closures, shard_map bodies) as a
    static leaf, the fused kernel consumes it directly, and the unfused /
    decode paths materialize it with jnp.asarray — so the fused and
    fallback paths cannot silently apply different tables."""

    def __init__(self, table):
        self.table = np.asarray(table, dtype=np.float32)

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


jax.tree_util.register_pytree_node(
    StaticTable, lambda t: ((), t), lambda aux, _: aux
)


def _rot_tables(rot, n, d, dtype):
    """cos/sin operands (n, d) in the compute dtype. The angle table is
    zero-padded to the head dim (zero angle = identity rotation), and the
    angles are cast to the compute dtype BEFORE cos/sin — exactly matching
    apply_rotary_emb's `angle_table.astype(t.dtype)` (ops/rotary.py:82) so
    the fused path is bit-compatible with the unfused one at f32.

    The table must be PAIR-CONSTANT (angle identical within each (2i, 2i+1)
    channel pair): the fused backward's inverse rotation computes
    (dy @ P) * sin, which equals the true VJP term (sin * dy) @ P^T only
    under that symmetry. Every table rotary.py produces satisfies it (the
    repeat-2 in `angles`); a foreign table that does not would produce a
    correct forward with silently wrong gradients, so it is rejected here."""
    table = rot.table
    assert table.shape[0] >= n, (table.shape, n)
    table = table[:n]
    if table.shape[1] < d:
        table = np.pad(table, ((0, 0), (0, d - table.shape[1])))
    assert np.array_equal(table[:, 0::2], table[:, 1::2]), (
        "fused rotary requires a pair-constant angle table "
        "(table[:, 0::2] == table[:, 1::2]); see ops/rotary.py:angles"
    )
    ang = jnp.asarray(table).astype(dtype)
    return jnp.cos(ang), jnp.sin(ang)


def _rot_block(t, cos, sin, P):
    """In-kernel rotary: t*cos + rotate_half(t)*sin via the P-matrix dot.
    f32 accumulation (Mosaic requires 32-bit matmul acc); every product is
    an exact signed copy, so the rounding back to the input dtype matches
    the out-of-kernel rotate_half bitwise."""
    return t * cos + jax.lax.dot_general(
        t, P, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(t.dtype) * sin


def _inv_rot_block(t, cosf, sinf, Pf):
    """VJP of _rot_block = rotation by -theta: the rotation is orthogonal
    (P^T = -P, and sin/cos are constant within each rotation pair)."""
    return t * cosf - jax.lax.dot_general(
        t, Pf, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * sinf


def _fused_qkv_fwd_kernel(
    q_ref, k_ref, v_ref, mask_ref, kmask_ref, cos_ref, sin_ref, p_ref, o_ref, lse_ref,
    *, sm_scale, causal, d, hpb,
):
    outs = []
    for j in range(hpb):
        sl = slice(j * d, (j + 1) * d)
        q, k, v = q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl]
        if cos_ref is not None:
            cos, sin, P = cos_ref[:], sin_ref[:], p_ref[:].astype(q.dtype)
            q, k, v = (_rot_block(t, cos, sin, P) for t in (q, k, v))
        n = q.shape[0]
        s = _masked_scores(
            q, k, sm_scale, mask_ref, kmask_ref,
            1 if causal else 2, 0, 0, n, n,
        )
        m = jnp.max(s, axis=-1, keepdims=True)
        p = _masked_exp(s, m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / l_safe
        outs.append(o.astype(o_ref.dtype))
        lse_ref[0, j] = jax.lax.transpose(m + jnp.log(l_safe), (1, 0))
    o_ref[0] = outs[0] if hpb == 1 else jnp.concatenate(outs, axis=-1)


def _fused_qkv_bwd_kernel(
    q_ref, k_ref, v_ref, mask_ref, kmask_ref, cos_ref, sin_ref, p_ref,
    do_ref, o_ref, lse_ref, dq_ref, dk_ref, dv_ref,
    *, sm_scale, causal, d, hpb,
):
    dqs, dks, dvs = [], [], []
    for j in range(hpb):
        sl = slice(j * d, (j + 1) * d)
        q, k, v = q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl]
        do = do_ref[0][:, sl]
        if cos_ref is not None:
            cos, sin, P = cos_ref[:], sin_ref[:], p_ref[:].astype(q.dtype)
            q, k, v = (_rot_block(t, cos, sin, P) for t in (q, k, v))
        n = q.shape[0]
        s = _masked_scores(
            q, k, sm_scale, mask_ref, kmask_ref,
            1 if causal else 2, 0, 0, n, n,
        )
        lse_row = jax.lax.transpose(lse_ref[0, j], (1, 0))  # (n, 1)
        p = _masked_exp(s, lse_row)
        dv_h = jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0][:, sl].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dq_h = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_h = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        if cos_ref is not None:
            cosf, sinf = cos_ref[:].astype(jnp.float32), sin_ref[:].astype(jnp.float32)
            Pf = p_ref[:].astype(jnp.float32)
            dq_h, dk_h, dv_h = (
                _inv_rot_block(t, cosf, sinf, Pf) for t in (dq_h, dk_h, dv_h)
            )
        dqs.append(dq_h.astype(dq_ref.dtype))
        dks.append(dk_h.astype(dk_ref.dtype))
        dvs.append(dv_h.astype(dv_ref.dtype))
    dq_ref[0] = dqs[0] if hpb == 1 else jnp.concatenate(dqs, axis=-1)
    dk_ref[0] = dks[0] if hpb == 1 else jnp.concatenate(dks, axis=-1)
    dv_ref[0] = dvs[0] if hpb == 1 else jnp.concatenate(dvs, axis=-1)


# one budget, two consumers: _call_plain hands it to Mosaic, and
# fused_qkv_supported derives the admissible n from it — keep in sync by
# construction
FUSED_VMEM_LIMIT_BYTES = 100 * 1024 * 1024


def _call_plain(kernel, grid, in_specs, out_specs, out_shape, operands, interpret, cost):
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",) * len(grid),
            # the head-group backward holds several (n, n) f32 temporaries
            # at once (s, p, dp, ds); the default 16 MiB scoped-vmem budget
            # is exceeded at n=1280 x 2 heads — v5e has 128 MiB physical
            vmem_limit_bytes=FUSED_VMEM_LIMIT_BYTES,
        ),
        cost_estimate=cost,
        interpret=interpret,
    )(*operands)


def fused_qkv_supported(n, heads, dim_head):
    """The packed path needs a lane-aligned whole-row block that fits VMEM
    and 128-aligned head groups. The n bound is derived from the backward's
    VMEM footprint instead of a fixed cap: per head group it materializes
    ~4 (n, n) f32 score-sized temporaries (s, p, dp, ds) x hpb unrolled
    heads, which must fit the 100 MB vmem_limit_bytes set in _call_plain
    (v5e has 128 MB physical) with ~20% headroom for the qkv/do/o blocks
    and double-buffered I/O. At d=64 (hpb=2) this admits n <= 1536
    (75.5 MB; verified to compile and run on v5e) and rejects n = 1792+;
    a fixed n <= 2048 cap used to pass this check yet fail to compile on
    real hardware."""
    hpb = max(1, 128 // dim_head)
    vmem_budget = int(FUSED_VMEM_LIMIT_BYTES * 0.8)
    bwd_temp_bytes = 4 * n * n * 4 * hpb
    return (
        n % 128 == 0
        and bwd_temp_bytes <= vmem_budget
        and (dim_head * hpb) % 128 == 0
        and heads % hpb == 0
        and (heads * dim_head) % 128 == 0
    )


def _fused_prep(qkv, key_mask, heads, dim_head, rot, pattern_mask):
    b, n, thd = qkv.shape
    d, h = dim_head, heads
    assert thd == 3 * h * d, (qkv.shape, heads, dim_head)
    hpb = max(1, 128 // d)
    assert fused_qkv_supported(n, h, d)
    mask_np = None
    if pattern_mask is not None:
        assert isinstance(pattern_mask, StaticMask)
        mask_np = pattern_mask.mask
        assert mask_np.shape == (n, n)
    mask_op, mask_spec = [], []
    if mask_np is not None:
        mask_op = [jnp.asarray(mask_np, jnp.int8)]
        mask_spec = [pl.BlockSpec((n, n), lambda bi, g: (0, 0))]
    km_op, km_spec = [], []
    if key_mask is not None:
        assert key_mask.shape == (b, n), (key_mask.shape, (b, n))
        km_op = [key_mask[:, None, :].astype(jnp.int32)]
        km_spec = [pl.BlockSpec((1, 1, n), lambda bi, g: (bi, 0, 0))]
    rot_op, rot_spec = [], []
    if rot is not None:
        cos, sin = _rot_tables(rot, n, d, qkv.dtype)
        from .rotary import _rotate_half_matrix

        rot_op = [cos, sin, jnp.asarray(_rotate_half_matrix(d))]
        rot_spec = [pl.BlockSpec((n, d), lambda bi, g: (0, 0))] * 2 + [
            pl.BlockSpec((d, d), lambda bi, g: (0, 0))
        ]
    return b, n, d, h, hpb, mask_op, mask_spec, km_op, km_spec, rot_op, rot_spec


def _fused_cost(b, n, d, h, dots, rot_dots, dtype_bytes):
    """``dots`` big (n, n, d) block dots + ``rot_dots`` rotate-half
    (n, d, d) P-dots per head (fwd: q/k/v rotation = 3; bwd: those plus the
    inverse rotation of the three gradients = 9 total across both)."""
    return pl.CostEstimate(
        flops=b * h * (dots * 2 * n * n * d + rot_dots * 2 * n * d * d),
        transcendentals=b * h * n * n,
        bytes_accessed=b * h * n * d * dtype_bytes * (3 + dots),
    )


def _fused_unpack(kernel, n_extra, mask_op, km_op, rot_op, **static):
    """Positional-ref adapter shared by the fused fwd/bwd pallas bodies:
    q/k/v, then the optional (pattern, key-mask, cos/sin/P) operands, then
    ``n_extra`` trailing inputs (bwd: do, o, lse), then the outputs."""

    def wrapped(*refs):
        split = 3 + len(mask_op) + len(km_op) + len(rot_op) + n_extra
        ins = list(refs[:split])
        outs = refs[split:]
        fixed, rest = ins[:3], ins[3:]
        mr = rest.pop(0) if mask_op else None
        kmr = rest.pop(0) if km_op else None
        cr = rest.pop(0) if rot_op else None
        sr = rest.pop(0) if rot_op else None
        pr = rest.pop(0) if rot_op else None
        return kernel(*fixed, mr, kmr, cr, sr, pr, *rest, *outs, **static)

    return wrapped


def _fused_qkv_fwd(qkv, key_mask, heads, dim_head, rot, causal, pattern_mask, sm_scale, interpret):
    (b, n, d, h, hpb, mask_op, mask_spec, km_op, km_spec, rot_op, rot_spec) = (
        _fused_prep(qkv, key_mask, heads, dim_head, rot, pattern_mask)
    )
    scale = d**-0.5 if sm_scale is None else sm_scale
    g = h // hpb
    w = hpb * d  # block width (a multiple of 128)
    hd = h * d

    def q_im(bi, gi):
        return (bi, 0, gi)

    def k_im(bi, gi):
        return (bi, 0, g + gi)

    def v_im(bi, gi):
        return (bi, 0, 2 * g + gi)

    in_specs = [
        pl.BlockSpec((1, n, w), q_im),
        pl.BlockSpec((1, n, w), k_im),
        pl.BlockSpec((1, n, w), v_im),
        *mask_spec, *km_spec, *rot_spec,
    ]
    wrapped = _fused_unpack(
        _fused_qkv_fwd_kernel, 0, mask_op, km_op, rot_op,
        sm_scale=scale, causal=causal, d=d, hpb=hpb,
    )

    o, lse = _call_plain(
        wrapped,
        grid=(b, g),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
            pl.BlockSpec((1, hpb, 1, n), lambda bi, gi: (bi, gi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), qkv.dtype),
            jax.ShapeDtypeStruct((b, h, 1, n), jnp.float32),
        ],
        operands=[qkv, qkv, qkv, *mask_op, *km_op, *rot_op],
        interpret=interpret,
        cost=_fused_cost(b, n, d, h, 2, 3 if rot_op else 0, qkv.dtype.itemsize),
    )
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def fused_qkv_attention(
    qkv, key_mask, heads, dim_head, rot=None, causal=True,
    pattern_mask=None, sm_scale=None, interpret=False,
):
    """Packed single-block attention: (b, n, 3*h*d) -> (b, n, h*d), rotary
    (q, k AND v — the reference's quirk, attention.py:63-64) applied inside
    the kernel from the static angle table ``rot`` (StaticTable). Covers
    the reference's dense causal + pad-mask semantics (attention.py:39-86)
    in the projection's own layout: no split/reshape/transpose ops touch
    HBM between the qkv projection and the output projection."""
    o, _ = _fused_qkv_fwd(
        qkv, key_mask, heads, dim_head, rot, causal, pattern_mask, sm_scale, interpret
    )
    return o


def _fused_fwd_rule(qkv, key_mask, heads, dim_head, rot, causal, pattern_mask, sm_scale, interpret):
    o, lse = _fused_qkv_fwd(
        qkv, key_mask, heads, dim_head, rot, causal, pattern_mask, sm_scale, interpret
    )
    return o, (qkv, key_mask, o, lse)


def _fused_bwd_rule(heads, dim_head, rot, causal, pattern_mask, sm_scale, interpret, res, do):
    qkv, key_mask, o, lse = res
    (b, n, d, h, hpb, mask_op, mask_spec, km_op, km_spec, rot_op, rot_spec) = (
        _fused_prep(qkv, key_mask, heads, dim_head, rot, pattern_mask)
    )
    scale = d**-0.5 if sm_scale is None else sm_scale
    g = h // hpb
    w = hpb * d
    hd = h * d

    in_specs = [
        pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
        pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, g + gi)),
        pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, 2 * g + gi)),
        *mask_spec, *km_spec, *rot_spec,
        pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
        pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
        pl.BlockSpec((1, hpb, 1, n), lambda bi, gi: (bi, gi, 0, 0)),
    ]

    wrapped = _fused_unpack(
        _fused_qkv_bwd_kernel, 3, mask_op, km_op, rot_op,
        sm_scale=scale, causal=causal, d=d, hpb=hpb,
    )

    dq, dk, dv = _call_plain(
        wrapped,
        grid=(b, g),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
            pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
            pl.BlockSpec((1, n, w), lambda bi, gi: (bi, 0, gi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), qkv.dtype),
            jax.ShapeDtypeStruct((b, n, hd), qkv.dtype),
            jax.ShapeDtypeStruct((b, n, hd), qkv.dtype),
        ],
        operands=[qkv, qkv, qkv, *mask_op, *km_op, *rot_op, do, o, lse],
        interpret=interpret,
        cost=_fused_cost(b, n, d, h, 5, 6 if rot_op else 0, qkv.dtype.itemsize),
    )
    dqkv = jnp.concatenate((dq, dk, dv), axis=-1)
    dkm = None if key_mask is None else np.zeros(key_mask.shape, jax.dtypes.float0)
    return (dqkv, dkm)


fused_qkv_attention.defvjp(_fused_fwd_rule, _fused_bwd_rule)
