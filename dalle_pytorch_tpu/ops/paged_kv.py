"""Block-paged decode KV cache: alloc / append / gather over fixed-size pages.

Ragged Paged Attention (PAPERS.md) is the TPU-native answer to the
batch-conditional cache-layout hack this repo carried (flat at batch 8,
4-D elsewhere — ops/attention.py:_decode_caches history): store K/V in
fixed-size pages of ``page_size`` tokens, reach them through a per-sequence
page table, and make the decode step's cache update a PAGE-LOCAL write. The
layout is then a property of the cache, not of the batch size:

- pools are ``(b, n_pages, page_size, h*d)`` — the minor two dims (one
  page) are identical at every batch size, so XLA's layout choice cannot
  re-tip per batch the way the flat/(b, L, h*d) vs 4-D/(b, L, h, d) ranks
  did (the root cause of serving throughput being non-monotone in batch:
  batch 32 measured 6,050 tok/s below batch 8's 6,832, BENCH_r05);
- the per-step append is a one-row scatter inside one page per sequence —
  never the whole-buffer dynamic-update-slice rewrite the 4-D layout
  compiled to (trace-measured 43% of the batch-8 decode program);
- the write index is PER SEQUENCE (``(b,)`` int32), so requests at
  different decode offsets share one step — continuous batching. The
  flat/4-D formats' scalar index cannot express that;
- the page table indirection (identity inside one jitted generation) is
  the seam a serving layer needs for page reuse / prefix sharing across
  requests without recompiling.

Tables hold GLOBAL physical page ids (PR 10): entry (b, l) names page
``g`` of the FLATTENED (b * n_pages, page_size, feat) pool view —
``g = row * n_pages + p`` for the identity mapping — so a table entry can
reference a page that physically lives in ANOTHER batch row's storage.
That is what makes cross-request prefix sharing a page-table indirection
(serving/prefix_cache.py maps a cache-hit request's prompt pages at the
publisher's physical pages, refcounted, copy-on-write on divergence)
instead of a cache redesign. Identity-mapped callers (every in-jit user:
generation, training-free decode, the batch-1 prefill caches) see
bit-identical behavior — the gather/append arithmetic only reshapes the
pool view, never the data. Sharded serving note: a pjit-sharded pool
would keep tables row-local (a global gather crosses shards); the
single-device serving engine is the consumer of the global form.

Two XLA formulations of the page gather were built and measured (CPU,
this box, 2026-08; pools (8, 10, 128, 1024) bf16, jitted, best of 50):

- ``take``   — ``jnp.take_along_axis`` down the page axis: 0.47 ms/gather.
  XLA fuses the row gather into the consuming attention einsum's operand
  read on TPU, so no (b, L, h*d) copy materializes in HBM.
- ``onehot`` — one-hot(table) matmul against the pool (gather as MXU
  work): 23.5 ms/gather on CPU, ~50x slower — the (b, n_pages, n_pages)
  one-hot contraction re-reads the whole pool per logical page. Kept for
  re-measurement (``DALLE_TPU_PAGED_GATHER=onehot``) because on TPU a
  skinny matmul sometimes beats the gather unit; the CPU loser's numbers
  stay recorded here either way.

A third option — extending the fused Pallas decode kernel
(ops/decode_attention.py) with page-table scalar prefetch — was REJECTED
without building it: that kernel is already a measured negative result for
this decode shape (~29 us/layer vs ~10 us for the XLA op chain it
replaces, v5e; its module docstring), and paging adds an indirection per
K/V block on top of the same skinny-MXU serialization. Revisit only if a
TPU sweep (bench.py --sweep) shows the take-gather path bound on gather
overhead rather than on page bytes.

All functions are pure array ops (no flax state); ops/attention.py owns
the cache variables and calls these.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .kv_policy import DEFAULT_PAGE_SIZE

# Every cache-tree leaf name that is POOL-SHAPED — (rows, n_pages, page,
# feat) storage addressed by global page ids. The serving engine's
# generic pool machinery (arena append, publish/COW/restore page copies,
# eviction resets, snapshot leaf enumeration) pattern-matches on THIS
# tuple, so a new pool kind (the int8 scale pools) rides every seam by
# construction instead of by N hand-updated name lists. CONTENT_KEYS are
# the K/V byte pools; SCALE_KEYS the parallel per-(token, head) scale
# pools that exist only under kv_quant="int8" (ops/kv_policy.py).
CONTENT_KEYS = ("cached_key_pages", "cached_value_pages")
SCALE_KEYS = ("cached_key_scale_pages", "cached_value_scale_pages")
POOL_LEAF_KEYS = CONTENT_KEYS + SCALE_KEYS

# dtype of the per-(token, head) scales — f32, like every QuantDense /
# QuantEmbed scale in ops/layers.py (the repo's one quant idiom)
SCALE_DTYPE = jnp.float32


def quantize_rows(rows: jnp.ndarray, heads: int):
    """Symmetric int8 quantization of K/V rows at APPEND time: ``rows``
    (b, n, heads * d) float -> (int8 rows (b, n, heads * d), f32 scales
    (b, n, heads)). Per-(token, head) granularity: each appended row
    owns its scale, stored in the parallel paged scale pool, so an
    append is position-local and IDEMPOTENT — re-appending the same row
    (preempt replay, the spec-decode reject-suffix overwrite) reproduces
    byte-identical pool content, which is what keeps every standing
    bitwise parity contract intact under quantization. (A literal
    one-scale-per-page scheme would need requantization as the page
    fills, breaking exactly that idempotence.) The arithmetic mirrors
    utils/quantize.py:quantize_kernel: amax/127 scale, zeros quantize
    with scale 1, round-to-nearest-even, clip to [-127, 127]."""
    b, n, hd = rows.shape
    d = hd // heads
    assert heads * d == hd, (rows.shape, heads)
    r = rows.astype(jnp.float32).reshape(b, n, heads, d)
    amax = jnp.max(jnp.abs(r), axis=-1)  # (b, n, heads)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(SCALE_DTYPE)
    q = jnp.clip(
        jnp.round(r / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q.reshape(b, n, hd), scale


def dequant(view: jnp.ndarray, scales: jnp.ndarray, dtype) -> jnp.ndarray:
    """THE dequantization formula, shared verbatim by the jnp reference
    path (gathered (b, W, h*d) int8 view + gathered (b, W, h) scales —
    ops/ragged_attention.py:reference_attend and the split decode path)
    and semantically by the Pallas kernel's in-register widen (same
    int8->f32 widen, same f32 scale multiply, per page instead of per
    view — ops/ragged_attention.py:_ragged_kernel). int8 values are
    exact in f32 and the scale multiply happens in f32 before the cast
    to the compute ``dtype``, so the formula is deterministic
    elementwise: identical pool bytes always dequantize to identical
    values, the keystone of the quantized bitwise-parity tier."""
    b, W, hd = view.shape
    h = scales.shape[-1]
    d = hd // h
    x = view.astype(jnp.float32).reshape(b, W, h, d) * (
        scales.astype(jnp.float32)[..., None]
    )
    return x.reshape(b, W, hd).astype(dtype)


def gather_variant() -> str:
    """``take`` (default) or ``onehot`` — see the measured comparison in the
    module docstring."""
    v = os.environ.get("DALLE_TPU_PAGED_GATHER", "take")
    if v not in ("take", "onehot"):
        raise ValueError(
            f"DALLE_TPU_PAGED_GATHER must be 'take' or 'onehot', got {v!r}"
        )
    return v


def num_pages(length: int, page_size: int = DEFAULT_PAGE_SIZE) -> int:
    """Pages needed to hold ``length`` tokens (ceil division)."""
    assert page_size > 0, page_size
    return -(-length // page_size)


def alloc(
    batch: int,
    length: int,
    feat: int,
    page_size: int = DEFAULT_PAGE_SIZE,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """A zeroed page pool covering ``length`` tokens:
    (batch, num_pages, page_size, feat)."""
    return jnp.zeros((batch, num_pages(length, page_size), page_size, feat), dtype)


def identity_table(batch: int, n_pages: int) -> jnp.ndarray:
    """(batch, n_pages) page table mapping logical page i of row r to
    GLOBAL physical page ``r * n_pages + i`` — row r's own i-th page in
    the flattened pool view. Identity is the invariant every in-jit user
    keeps (resize_kv rebuilds it to truncate/grow pools and tables in
    lockstep); the serving layer's prefix cache
    (serving/prefix_cache.py) is the one consumer that remaps entries
    across rows."""
    return (
        jnp.arange(batch, dtype=jnp.int32)[:, None] * n_pages
        + jnp.arange(n_pages, dtype=jnp.int32)[None]
    )


def flat_view(pool: jnp.ndarray) -> jnp.ndarray:
    """The (rows * n_pages, page, feat) GLOBAL view of a pool — the id
    space page tables index. A pure reshape (no data movement): physical
    page ``g`` is row ``g // n_pages``'s page ``g % n_pages``."""
    rows, n_p, page, feat = pool.shape
    return pool.reshape(rows * n_p, page, feat)


def append(
    pool: jnp.ndarray,
    table: jnp.ndarray,
    index: jnp.ndarray,
    rows: jnp.ndarray,
    limit: jnp.ndarray = None,
) -> jnp.ndarray:
    """Write ``rows`` (b, n, feat) at per-sequence positions
    ``index`` (b,) .. index+n into the paged ``pool`` (rows, n_pages, page,
    feat) through ``table`` (b, n_pages) holding GLOBAL physical page ids.
    Returns the updated pool. ``pool`` may carry MORE storage rows than the
    table has sequences (the serving engine's prefix-cache arena rides as
    extra rows addressable only through remapped table entries); the
    sequence batch is the TABLE's.

    Positions may cross page boundaries mid-block (a prefill block spans
    ceil(n/page) pages); each row lands in page ``pos // page`` at offset
    ``pos % page``. Out-of-capacity positions are dropped, matching the
    flat path's dynamic_update_slice clamp semantics at the buffer edge
    only in never-read positions (callers guarantee index + n <= capacity).

    ``limit`` (b,) int32, optional: per-sequence VALID row count — rows
    j >= limit[b] are dropped, never written. This is the ragged fused
    iteration's write mask (ops/ragged_attention.py): every cache row
    receives the same padded (b, n, feat) block, but a decode row commits
    one position, a prefill chunk its own width, an idle row nothing.
    """
    n_rows, n_p, page, feat = pool.shape
    l_pages = table.shape[1]
    n = rows.shape[1]
    pos = index[:, None] + jnp.arange(n, dtype=index.dtype)[None, :]  # (b, n)
    logical = pos // page
    off = pos % page
    phys = jnp.take_along_axis(table, jnp.minimum(logical, l_pages - 1), axis=1)
    # drop (not clamp) genuinely out-of-capacity rows
    valid = logical < l_pages
    if limit is not None:
        valid = valid & (
            jnp.arange(n, dtype=jnp.int32)[None, :] < limit[:, None]
        )
    phys = jnp.where(valid, phys, n_rows * n_p)  # OOB sentinel, mode="drop"
    flat = flat_view(pool).at[phys, off].set(rows, mode="drop")
    return flat.reshape(pool.shape)


def reset_rows(pool: jnp.ndarray, rows) -> jnp.ndarray:
    """Zero the page pools of the given SLOT rows — the eviction reset.

    A preempted/completed request's pages must not leak stale K/V into the
    slot's next tenant: the serving engine re-prefills the slot from scratch,
    and prefill only overwrites positions [0, T), so stale rows beyond the
    new request's frontier would otherwise survive under the (zeros-masked)
    attention sweep contract. ``rows`` is an int row index or a sequence of
    them; works on any (b, ...) pool-shaped leaf.

    Refcount discipline (serving/prefix_cache.py): this zeros a row's
    NATIVE storage only. Shared prefix pages live in dedicated ARENA rows
    past the slot rows and are reachable only through remapped table
    entries, so evicting a slot that references refcounted shared pages
    must pair this with ``reset_table_rows`` — dropping the REFERENCE —
    and must never name an arena row here: arena content is owned by the
    prefix index and reclaimed only by its own (refcount == 0) eviction.
    The engine asserts the row bound (``Engine._release_slot``); the
    sibling-bit-parity regression lives in tests/test_prefix_cache.py."""
    return pool.at[jnp.asarray(rows)].set(0)


def reset_table_rows(table: jnp.ndarray, rows) -> jnp.ndarray:
    """Restore the identity mapping (global ids ``r * n_pages + i``) for
    the given batch rows of a page table. Eviction hands the slot's own
    physical pages back as a pristine identity-mapped pool (the invariant
    every in-jit user keeps — see ``identity_table``) and, for a slot
    holding shared prefix pages, DROPS the cross-row references without
    touching the shared storage (the refcount-only half of the eviction;
    see ``reset_rows``). The identity stride is ``table.shape[1]``: the
    pool's page axis must equal the table's logical width (arena capacity
    extends the pool's ROW axis, never its page axis)."""
    b, n_p = table.shape
    r = jnp.atleast_1d(jnp.asarray(rows, dtype=table.dtype))
    ident = r[:, None] * n_p + jnp.arange(n_p, dtype=table.dtype)[None]
    return table.at[r].set(ident)


def copy_pages_across(
    dst_pool: jnp.ndarray, src_pool: jnp.ndarray, src, dst, valid=None
) -> jnp.ndarray:
    """Copy whole physical pages ``src`` (global ids into ``src_pool``'s
    flat view) onto pages ``dst`` of ``dst_pool``, zeroing destination
    rows past ``valid`` (per-page valid row counts; None = all rows).
    One gather + one scatter per call — the prefix cache's primitive for
    publish (slot pages -> arena), copy-on-write (shared terminal page ->
    the diverging slot's native page; same pool both sides, see
    ``copy_pages``) and the split engine's hit restore (batched arena ->
    a private batch-1 prefill cache). Destination rows at or past
    ``valid[i]`` are ZEROED, not preserved: a published terminal page
    must not leak the publisher's image K/V, and a COW'd page must
    satisfy the zeros-past-frontier sweep contract even when the
    destination page held stale content.

    An OUT-OF-RANGE ``dst`` id (>= the destination's page count) DROPS
    that copy entirely (scatter mode="drop") — the padding convention of
    the serving engine's fixed-shape donated copy jit
    (serving/engine.py:_copy_pages_jit): call vectors pad to one static
    length with dst = the sentinel, so every publish/COW/restore shares
    one compile signature. In-range ids behave exactly as before (the
    drop mode only changes what out-of-range writes do)."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    content = flat_view(src_pool)[src]  # (k, page, feat)
    if valid is not None:
        page = src_pool.shape[2]
        keep = (
            jnp.arange(page, dtype=jnp.int32)[None]
            < jnp.asarray(valid, jnp.int32)[:, None]
        )
        content = jnp.where(keep[..., None], content, 0)
    return (
        flat_view(dst_pool).at[dst].set(content, mode="drop")
        .reshape(dst_pool.shape)
    )


def copy_pages(pool: jnp.ndarray, src, dst, valid=None) -> jnp.ndarray:
    """``copy_pages_across`` within one pool — see its docstring."""
    return copy_pages_across(pool, pool, src, dst, valid)


def gather(pool: jnp.ndarray, table: jnp.ndarray, variant=None) -> jnp.ndarray:
    """Assemble the logical cache view (b, l_pages * page, feat) from the
    paged pool through a GLOBAL-id table (b, l_pages) — a table entry may
    name a page in ANY storage row, which is what lets the serving prefix
    cache map one physical page into many sequences' views. The ``take``
    variant is the production path (the row gather fuses into the
    consuming einsum); ``onehot`` is the measured-slower MXU formulation
    kept for TPU re-measurement — numbers in the module docstring."""
    n_rows, n_p, page, feat = pool.shape
    b, l_pages = table.shape
    if variant is None:
        variant = gather_variant()
    flat = flat_view(pool)
    if variant == "onehot":
        oh = jax.nn.one_hot(table, n_rows * n_p, dtype=pool.dtype)
        g = jnp.einsum("blg,gpf->blpf", oh, flat)
    else:
        g = jnp.take(flat, table, axis=0)  # (b, l_pages, page, feat)
    return g.reshape(b, l_pages * page, feat)
