"""Block-paged decode KV cache: alloc / append / gather over fixed-size pages.

Ragged Paged Attention (PAPERS.md) is the TPU-native answer to the
batch-conditional cache-layout hack this repo carried (flat at batch 8,
4-D elsewhere — ops/attention.py:_decode_caches history): store K/V in
fixed-size pages of ``page_size`` tokens, reach them through a per-sequence
page table, and make the decode step's cache update a PAGE-LOCAL write. The
layout is then a property of the cache, not of the batch size:

- pools are ``(b, n_pages, page_size, h*d)`` — the minor two dims (one
  page) are identical at every batch size, so XLA's layout choice cannot
  re-tip per batch the way the flat/(b, L, h*d) vs 4-D/(b, L, h, d) ranks
  did (the root cause of serving throughput being non-monotone in batch:
  batch 32 measured 6,050 tok/s below batch 8's 6,832, BENCH_r05);
- the per-step append is a one-row scatter inside one page per sequence —
  never the whole-buffer dynamic-update-slice rewrite the 4-D layout
  compiled to (trace-measured 43% of the batch-8 decode program);
- the write index is PER SEQUENCE (``(b,)`` int32), so requests at
  different decode offsets share one step — continuous batching. The
  flat/4-D formats' scalar index cannot express that;
- the page table indirection (identity inside one jitted generation) is
  the seam a serving layer needs for page reuse / prefix sharing across
  requests without recompiling.

Two XLA formulations of the page gather were built and measured (CPU,
this box, 2026-08; pools (8, 10, 128, 1024) bf16, jitted, best of 50):

- ``take``   — ``jnp.take_along_axis`` down the page axis: 0.47 ms/gather.
  XLA fuses the row gather into the consuming attention einsum's operand
  read on TPU, so no (b, L, h*d) copy materializes in HBM.
- ``onehot`` — one-hot(table) matmul against the pool (gather as MXU
  work): 23.5 ms/gather on CPU, ~50x slower — the (b, n_pages, n_pages)
  one-hot contraction re-reads the whole pool per logical page. Kept for
  re-measurement (``DALLE_TPU_PAGED_GATHER=onehot``) because on TPU a
  skinny matmul sometimes beats the gather unit; the CPU loser's numbers
  stay recorded here either way.

A third option — extending the fused Pallas decode kernel
(ops/decode_attention.py) with page-table scalar prefetch — was REJECTED
without building it: that kernel is already a measured negative result for
this decode shape (~29 us/layer vs ~10 us for the XLA op chain it
replaces, v5e; its module docstring), and paging adds an indirection per
K/V block on top of the same skinny-MXU serialization. Revisit only if a
TPU sweep (bench.py --sweep) shows the take-gather path bound on gather
overhead rather than on page bytes.

All functions are pure array ops (no flax state); ops/attention.py owns
the cache variables and calls these.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .kv_policy import DEFAULT_PAGE_SIZE


def gather_variant() -> str:
    """``take`` (default) or ``onehot`` — see the measured comparison in the
    module docstring."""
    v = os.environ.get("DALLE_TPU_PAGED_GATHER", "take")
    if v not in ("take", "onehot"):
        raise ValueError(
            f"DALLE_TPU_PAGED_GATHER must be 'take' or 'onehot', got {v!r}"
        )
    return v


def num_pages(length: int, page_size: int = DEFAULT_PAGE_SIZE) -> int:
    """Pages needed to hold ``length`` tokens (ceil division)."""
    assert page_size > 0, page_size
    return -(-length // page_size)


def alloc(
    batch: int,
    length: int,
    feat: int,
    page_size: int = DEFAULT_PAGE_SIZE,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """A zeroed page pool covering ``length`` tokens:
    (batch, num_pages, page_size, feat)."""
    return jnp.zeros((batch, num_pages(length, page_size), page_size, feat), dtype)


def identity_table(batch: int, n_pages: int) -> jnp.ndarray:
    """(batch, n_pages) page table mapping logical page i -> physical page i
    within the sequence's own pool row. Identity is the invariant every
    in-jit user keeps (resize_kv relies on it to truncate/grow pools and
    tables in lockstep); a serving layer remapping pages would manage its
    own tables."""
    return jnp.broadcast_to(
        jnp.arange(n_pages, dtype=jnp.int32)[None], (batch, n_pages)
    )


def append(
    pool: jnp.ndarray,
    table: jnp.ndarray,
    index: jnp.ndarray,
    rows: jnp.ndarray,
    limit: jnp.ndarray = None,
) -> jnp.ndarray:
    """Write ``rows`` (b, n, feat) at per-sequence positions
    ``index`` (b,) .. index+n into the paged ``pool`` (b, n_pages, page, feat)
    through ``table`` (b, n_pages). Returns the updated pool.

    Positions may cross page boundaries mid-block (a prefill block spans
    ceil(n/page) pages); each row lands in page ``pos // page`` at offset
    ``pos % page``. Out-of-capacity positions are dropped, matching the
    flat path's dynamic_update_slice clamp semantics at the buffer edge
    only in never-read positions (callers guarantee index + n <= capacity).

    ``limit`` (b,) int32, optional: per-sequence VALID row count — rows
    j >= limit[b] are dropped, never written. This is the ragged fused
    iteration's write mask (ops/ragged_attention.py): every cache row
    receives the same padded (b, n, feat) block, but a decode row commits
    one position, a prefill chunk its own width, an idle row nothing.
    """
    b, n_p, page, feat = pool.shape
    n = rows.shape[1]
    pos = index[:, None] + jnp.arange(n, dtype=index.dtype)[None, :]  # (b, n)
    logical = pos // page
    off = pos % page
    phys = jnp.take_along_axis(table, jnp.minimum(logical, n_p - 1), axis=1)
    # drop (not clamp) genuinely out-of-capacity rows
    valid = logical < n_p
    if limit is not None:
        valid = valid & (
            jnp.arange(n, dtype=jnp.int32)[None, :] < limit[:, None]
        )
    phys = jnp.where(valid, phys, n_p)
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, n))
    return pool.at[bidx, phys, off].set(rows, mode="drop")


def reset_rows(pool: jnp.ndarray, rows) -> jnp.ndarray:
    """Zero the page pools of the given batch rows — the eviction reset.

    A preempted/completed request's pages must not leak stale K/V into the
    slot's next tenant: the serving engine re-prefills the slot from scratch,
    and prefill only overwrites positions [0, T), so stale rows beyond the
    new request's frontier would otherwise survive under the (zeros-masked)
    attention sweep contract. ``rows`` is an int row index or a sequence of
    them; works on any (b, ...) pool-shaped leaf."""
    return pool.at[jnp.asarray(rows)].set(0)


def reset_table_rows(table: jnp.ndarray, rows) -> jnp.ndarray:
    """Restore the identity mapping for the given batch rows of a page
    table. Eviction hands the slot's physical pages back as a pristine
    identity-mapped pool (the invariant every in-jit user keeps — see
    ``identity_table``); a serving layer doing cross-slot page remapping
    would manage its own tables instead."""
    b, n_p = table.shape
    ident = jnp.arange(n_p, dtype=table.dtype)
    return table.at[jnp.asarray(rows)].set(ident)


def gather(pool: jnp.ndarray, table: jnp.ndarray, variant=None) -> jnp.ndarray:
    """Assemble the logical cache view (b, n_pages * page, feat) from the
    paged pool. The ``take`` variant is the production path (the row gather
    fuses into the consuming einsum); ``onehot`` is the measured-slower
    MXU formulation kept for TPU re-measurement — numbers in the module
    docstring."""
    b, n_p, page, feat = pool.shape
    if variant is None:
        variant = gather_variant()
    if variant == "onehot":
        oh = jax.nn.one_hot(table, n_p, dtype=pool.dtype)  # (b, L_pages, n_p)
        g = jnp.einsum("bln,bnpf->blpf", oh, pool)
    else:
        g = jnp.take_along_axis(pool, table[:, :, None, None], axis=1)
    return g.reshape(b, n_p * page, feat)
