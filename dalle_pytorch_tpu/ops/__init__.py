from . import kv_policy, masks, paged_kv, rotary
from .attention import PatternAttention, dense_attend
from .flash_attention import StaticMask, flash_attention
from .layers import (
    FeedForward,
    GMLPBlock,
    LayerScale,
    PreNorm,
    PreShiftToken,
    SpatialGatingUnit,
    divide_max,
    layer_scale_init,
    shift_tokens,
    stable_softmax,
)
from .moe import MoEFeedForward
from .reversible import reversible_forward_only, reversible_sequence
from .ring_attention import ring_attention, ulysses_attend
from .rotary import apply_rotary_emb, dalle_rotary_table

__all__ = [
    "kv_policy",
    "masks",
    "paged_kv",
    "rotary",
    "PatternAttention",
    "dense_attend",
    "StaticMask",
    "flash_attention",
    "MoEFeedForward",
    "ring_attention",
    "ulysses_attend",
    "FeedForward",
    "GMLPBlock",
    "LayerScale",
    "PreNorm",
    "PreShiftToken",
    "SpatialGatingUnit",
    "divide_max",
    "layer_scale_init",
    "shift_tokens",
    "stable_softmax",
    "reversible_forward_only",
    "reversible_sequence",
    "apply_rotary_emb",
    "dalle_rotary_table",
]
