"""Ragged paged attention: one attention program for a mixed
prefill+decode iteration ("Ragged Paged Attention", PAPERS.md).

The serving engine's fused iteration (serving/engine.py:_iteration_jit)
hands every cache row a DESCRIPTOR — (kind, start, length, page table) —
padded to one fixed iteration shape: a (B, W) token block where row b's
valid tokens occupy columns [0, length[b]) at positions
start[b] .. start[b] + length[b]. A decode row is length 1, a prefill
chunk up to W, an idle row 0 — raggedness is DATA, not shape, so every
steady mix of prefills and decodes shares one compile signature and the
whole iteration is a single device dispatch (final-chunk iterations are
the one extra, warm-compiled class; serving/engine.py:_iteration_jit).

Two implementations of the attention core — the attention layer
(``PatternAttention._decode_attend_paged``) picks via ``use_kernel``:

- ``reference_attend`` — plain jnp: ``paged_kv.gather`` assembles the
  logical (b, W_cache, h*d) view and ``ops/attention.py:
  cache_block_attend`` does the masked block attention. This is the
  tier-1 path (CPU, ``JAX_PLATFORMS=cpu``) and, by construction, shares
  every einsum with the split prefill-chunk/decode paths — which is what
  makes fused-vs-split ENGINE bit-parity exact for f32 models on CPU
  — the parity tier; bf16 programs round ~1 ulp apart across program
  shapes under XLA fusion — (pinned by
  tests/test_ragged_attention.py). Padding rows cost compute, never
  correctness: invalid query columns produce garbage that the caller
  discards, and their K/V is never written (``paged_kv.append``'s
  per-row ``limit``).

- ``kernel_attend`` — a Pallas TPU kernel streaming K/V PAGES through
  VMEM with an online-softmax accumulator, the page table + per-row
  (start, length) descriptors riding scalar prefetch: the page index map
  dereferences the table (each grid step fetches a DISTINCT physical
  page, so Mosaic's DMA pipelining is preserved — unlike the
  re-fetch-last-block pattern ops/flash_attention.py measured 23x slow),
  and pages past a row's frontier skip their dots. Causal "full" masking
  is analytic in-kernel; non-"full" patterns and key-padding masks take
  the reference path. TPU-only by default (``DALLE_TPU_RAGGED_KERNEL``
  forces it either way; interpret mode runs it anywhere for the parity
  sweeps in tests/test_ragged_attention.py). Kernel-vs-reference is an
  allclose contract (online softmax reassociates the reduction); the
  BIT-parity contracts all live on the reference path.

Width-1 note: the fused block computes EVERY row at the padded width W,
so a 1-token prefill tail or a decode row is just a mostly-masked row of
a gemm-shaped block — the fused path needs no 1-token-tail merge
(``cache_block_attend`` additionally pads genuine width-1 blocks to
width 2, so even W == 1 descriptors stay bit-consistent with wider
blocks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .jax_compat import tpu_compiler_params

NEG_INF = -1e30
LANES = 128


def use_kernel(causal_full: bool, has_key_mask: bool) -> bool:
    """Kernel eligibility for this call: analytic causal-"full" masking
    only (other patterns keep their mask-row semantics on the reference
    path), no runtime key mask, and a TPU backend unless
    ``DALLE_TPU_RAGGED_KERNEL`` forces either way (the shared tri-state
    gate, ops/kv_policy.py:tpu_auto_env)."""
    from .kv_policy import tpu_auto_env

    return (
        causal_full
        and not has_key_mask
        and tpu_auto_env("DALLE_TPU_RAGGED_KERNEL")
    )


# ------------------------------------------------------------- reference


def reference_attend(q, k_pool, v_pool, table, allowed, stable=False,
                     k_scales=None, v_scales=None):
    """The jnp oracle: gather the paged pools into the logical cache view
    and run the ONE shared masked-block attention. q (b, n, h, d)
    pre-scaled (rotary already applied); pools (b, n_p, page, h*d);
    ``allowed`` broadcastable to (b, 1, n, W_cache). Bitwise identical to
    the split paths' attention core by construction — both are
    ``cache_block_attend`` on the same gathered view. Quantized pools
    (int8 content + parallel (b, n_p, page, h) scale pools; ``k_scales``
    / ``v_scales``) dequantize the gathered view through the ONE shared
    formula (``paged_kv.dequant``) before the attention core — the same
    gather + dequant the split decode path runs, so fused-vs-split
    bitwise parity survives quantization unchanged."""
    from . import paged_kv
    from .attention import cache_block_attend

    k_cache = paged_kv.gather(k_pool, table)  # (b, W, h*d)
    v_cache = paged_kv.gather(v_pool, table)
    if k_scales is not None:
        k_cache = paged_kv.dequant(
            k_cache, paged_kv.gather(k_scales, table), q.dtype
        )
        v_cache = paged_kv.dequant(
            v_cache, paged_kv.gather(v_scales, table), q.dtype
        )
    return cache_block_attend(q, k_cache, v_cache, allowed, stable)


# ---------------------------------------------------------------- kernel


def _ragged_kernel(
    scalar_ref, q_ref, k_ref, v_ref, *refs,
    heads, dim_head, page, n_pages, width, quant,
):
    """One (row, page) grid step: q_ref (1, W, h*d) is row b's whole
    padded block, k_ref/v_ref (1, page, h*d) one physical page of the
    FLATTENED (rows * n_pages, page, h*d) pool view — the table holds
    GLOBAL page ids (ops/paged_kv.py), so a grid step can stream a page
    that physically lives in another row's storage (or the prefix-cache
    arena) — (selected by the TABLE in the index map). Per-head dots with running
    (max, denom, acc) scratch; analytic causal masking from the row's
    ``start`` descriptor; pages past the row's frontier skip compute
    (their DMA still streams — affine-in-j index maps keep Mosaic's
    pipeline; the skipped page's bytes are the price of raggedness-as-
    data).

    ``quant``: int8 pages with parallel per-(token, head) scale pages
    (ks_ref/vs_ref, (1, page, h) f32, selected by the SAME table entry
    so a shared prefix-arena page brings its own scales). Dequantization
    is IN-KERNEL, fused with the page stream: the int8 block widens to
    f32 in registers and multiplies its scale column before the dots —
    the same int8->f32-widen * f32-scale formula as ``paged_kv.dequant``
    — so the kernel streams half the KV bytes per page (plus the small
    h/(h*d) scale stream) and never materializes a dequantized cache."""
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    b_i, j = pl.program_id(0), pl.program_id(1)
    start = scalar_ref[b_i, n_pages]
    # frontier: the highest position this block can attend is its own
    # last VALID query, start + length - 1 (causal); idle rows
    # (length == 0) still visit page 0 so every query row stays finite
    length = scalar_ref[b_i, n_pages + 1]
    last_pos = start + jnp.maximum(length, 1) - 1

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j * page <= last_pos)
    def _():
        # (W, page) causal mask for this page: key position j*page + c
        # visible to query row i at position start + i
        qpos = jax.lax.broadcasted_iota(jnp.int32, (width, page), 0) + start
        kpos = jax.lax.broadcasted_iota(jnp.int32, (width, page), 1) + j * page
        visible = kpos <= qpos
        for h_ in range(heads):
            lo = h_ * dim_head
            qh = q_ref[0, :, lo:lo + dim_head]              # (W, d)
            kh = k_ref[0, :, lo:lo + dim_head]              # (page, d)
            vh = v_ref[0, :, lo:lo + dim_head]
            if quant:
                # in-register widen + scale: the shared dequant formula
                # (paged_kv.dequant) applied to one streamed page —
                # INCLUDING its final cast to the compute dtype, so the
                # kernel sees the same rounded K/V values the reference
                # path's gathered-view dequant produces (on a bf16
                # compute tier an uncast f32 product would diverge from
                # the split path in low bits; f32 tiers are unaffected)
                kh = (
                    kh.astype(jnp.float32) * ks_ref[0, :, h_:h_ + 1]
                ).astype(o_ref.dtype)
                vh = (
                    vh.astype(jnp.float32) * vs_ref[0, :, h_:h_ + 1]
                ).astype(o_ref.dtype)
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                               # (W, page)
            s = jnp.where(visible, s, NEG_INF)
            m_prev = m_scr[h_, :, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.where(s > 0.5 * NEG_INF, jnp.exp(s - m_new), 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_scr[h_, :, 0:1] = (
                l_scr[h_, :, 0:1] * corr + jnp.sum(p, axis=-1, keepdims=True)
            )
            m_scr[h_, :, 0:1] = m_new
            acc_scr[h_] = acc_scr[h_] * corr + jax.lax.dot_general(
                p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(j == n_pages - 1)
    def _():
        for h_ in range(heads):
            l = l_scr[h_, :, 0:1]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, :, h_ * dim_head:(h_ + 1) * dim_head] = (
                acc_scr[h_] / l_safe
            ).astype(o_ref.dtype)


def kernel_attend(q, k_pool, v_pool, table, start, length, interpret=False,
                  k_scales=None, v_scales=None):
    """Pallas ragged paged attention, causal-"full" masking. q (b, n, h, d)
    pre-scaled; returns (b, n, h, d). The pools are streamed through their
    FLATTENED (rows * n_pages, page, h*d) global view — the id space the
    table indexes (ops/paged_kv.py) — so pools carrying prefix-cache arena
    rows beyond the query batch work unchanged. ``k_scales``/``v_scales``
    (both or neither): int8 pools with parallel (b, n_p, page, h) f32
    scale pools — two more streamed operands riding the SAME table
    dereference, dequantized in-kernel (see the kernel docstring). The
    scale blocks' h-lane minor dim under-fills the 128-lane tile for
    small head counts (VMEM padding, not HBM traffic); a bitcast-packed
    scales-in-page layout is the known upgrade if a TPU profile shows
    the scale stream mattering next to the halved KV bytes."""
    from . import paged_kv

    b, n, h, d = q.shape
    _, n_p, page, hd = k_pool.shape
    l_pages = table.shape[1]
    assert hd == h * d, (k_pool.shape, (h, d))
    quant = k_scales is not None
    assert (k_scales is None) == (v_scales is None)
    qf = q.reshape(b, n, hd)
    k_flat = paged_kv.flat_view(k_pool)
    v_flat = paged_kv.flat_view(v_pool)
    # descriptor payload: per-row [table row | start | length], int32 —
    # the page index map dereferences s[b, j] (a GLOBAL page id into the
    # flat view); the kernel body reads the (start, length) tail
    scalar = jnp.concatenate(
        (table.astype(jnp.int32), start[:, None].astype(jnp.int32),
         length[:, None].astype(jnp.int32)), axis=1,
    )

    kernel = functools.partial(
        _ragged_kernel, heads=h, dim_head=d, page=page, n_pages=l_pages,
        width=n, quant=quant,
    )
    # the page-table indirection: grid step (bi, j) streams PHYSICAL
    # page table[bi, j] of the flat view — possibly another row's
    # storage or a shared prefix-cache arena page
    # (serving/prefix_cache.py); each grid step still fetches a
    # distinct page, preserving DMA pipelining
    page_spec = pl.BlockSpec((1, page, hd), lambda bi, j, s: (s[bi, j], 0, 0))
    in_specs = [
        pl.BlockSpec((1, n, hd), lambda bi, j, s: (bi, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [scalar, qf, k_flat, v_flat]
    kv_bytes = b * l_pages * page * hd * 2 * k_pool.dtype.itemsize
    if quant:
        # scale pages ride the same indirection as their content pages
        scale_spec = pl.BlockSpec(
            (1, page, h), lambda bi, j, s: (s[bi, j], 0, 0)
        )
        in_specs += [scale_spec, scale_spec]
        operands += [
            paged_kv.flat_view(k_scales), paged_kv.flat_view(v_scales),
        ]
        kv_bytes += (
            b * l_pages * page * h * 2 * k_scales.dtype.itemsize
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, l_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, n, hd), lambda bi, j, s: (bi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((h, n, LANES), jnp.float32),
                pltpu.VMEM((h, n, LANES), jnp.float32),
                pltpu.VMEM((h, n, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n, hd), q.dtype),
        # rows are independent; the page dimension accumulates in order
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * h * n * l_pages * page * d * 2,
            transcendentals=b * h * n * l_pages * page,
            bytes_accessed=kv_bytes + 2 * b * n * hd * q.dtype.itemsize,
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, n, h, d)


