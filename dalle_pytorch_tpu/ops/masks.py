"""Static attention-pattern masks for the mixed text+image sequence.

The reference implements each sparse pattern with dynamic padding, unfolds and
per-forward mask construction (attention.py:90-384). On TPU everything under
jit must be shape-static, so instead each pattern is expressed once, at model
build time, as a boolean (L, L) "may-attend" matrix over the fixed internal
sequence of length L = text_len + image_fmap_size**2 (text_len includes
<bos>). The efficient kernels (axial grouping, conv patches, block-sparse
Pallas) must agree exactly with these masks — that's the parity contract the
tests enforce — and the KV-cached decode path simply gathers rows from them.

True = query row may attend to key column. Key-padding masks are applied
separately at runtime.
"""

from __future__ import annotations

import numpy as np


def causal_mask(n: int) -> np.ndarray:
    """Dense causal: j <= i (reference attention.py:76-79)."""
    return np.tril(np.ones((n, n), dtype=bool))


def _image_query_grid(text_len: int, image_size: int):
    img_seq_len = image_size**2
    p = np.arange(img_seq_len)
    return p // image_size, p % image_size, img_seq_len, text_len + img_seq_len


def axial_mask(text_len: int, image_size: int, axis: int) -> np.ndarray:
    """Axial row/col attention (reference attention.py:211-321).

    Text queries: causal over text. Image query (r, c): all text keys, plus
    image keys along the same row (axis=0) with c' <= c, or the same column
    (axis=1) with r' <= r.
    """
    assert axis in (0, 1)
    row, col, img_seq_len, total = _image_query_grid(text_len, image_size)
    mask = np.zeros((total, total), dtype=bool)
    mask[:text_len, :text_len] = causal_mask(text_len)
    # image -> all text
    mask[text_len:, :text_len] = True
    # image -> image along the axis
    if axis == 0:
        allowed = (row[:, None] == row[None, :]) & (col[:, None] >= col[None, :])
    else:
        allowed = (col[:, None] == col[None, :]) & (row[:, None] >= row[None, :])
    mask[text_len:, text_len:] = allowed
    return mask


def conv_mask(
    text_len: int, image_size: int, kernel_size: int = 5, dilation: int = 1
) -> np.ndarray:
    """Convolution-like local attention (reference attention.py:90-207).

    Image query (r, c) attends to image keys inside its dilated kernel_size x
    kernel_size window whose flat index is <= its own, plus all text. Text
    queries: causal over text.
    """
    assert kernel_size % 2 == 1, "kernel size must be odd"
    row, col, img_seq_len, total = _image_query_grid(text_len, image_size)
    pad = ((kernel_size - 1) * dilation + 1) // 2

    mask = np.zeros((total, total), dtype=bool)
    mask[:text_len, :text_len] = causal_mask(text_len)
    mask[text_len:, :text_len] = True

    dr = np.abs(row[:, None] - row[None, :])
    dc = np.abs(col[:, None] - col[None, :])
    in_window = (
        (dr <= pad)
        & (dc <= pad)
        & (dr % dilation == 0)
        & (dc % dilation == 0)
    )
    q_idx = np.arange(img_seq_len)
    causal = q_idx[:, None] >= q_idx[None, :]
    mask[text_len:, text_len:] = in_window & causal
    return mask


def block_sparse_layout(
    seq_len: int,
    block_size: int = 16,
    text_seq_len: int = 256,
    num_random_blocks: int | None = None,
    num_local_blocks: int = 4,
    causal: bool = True,
    seed: int = 0,
) -> np.ndarray:
    """Block layout with DeepSpeed VariableSparsityConfig semantics
    (reference attention.py:325-351): a sliding window of ``num_local_blocks``
    previous blocks, global blocks covering the text prefix (attending and
    attended bidirectionally), and ``num_random_blocks`` random blocks per
    query block. Random choices are drawn once from a seeded RNG so the
    layout is static across compiles — matching DeepSpeed, which also builds
    its layout at init.

    Returns (nb, nb) bool where nb = ceil(seq_len / block_size).
    """
    nb = -(-seq_len // block_size)
    if num_random_blocks is None:
        num_random_blocks = max(seq_len // block_size // 4, 0)
    num_global = -(-text_seq_len // block_size)

    layout = np.zeros((nb, nb), dtype=bool)
    rng = np.random.RandomState(seed)

    for qb in range(nb):
        lo = max(0, qb - num_local_blocks + 1)
        layout[qb, lo : qb + 1] = True
        # random blocks (causal: only past blocks are useful)
        hi = qb + 1 if causal else nb
        if num_random_blocks > 0 and hi > 0:
            picks = rng.choice(hi, size=min(num_random_blocks, hi), replace=False)
            layout[qb, picks] = True

    # global text-prefix blocks: global rows and global columns
    layout[:num_global, :] = True
    layout[:, :num_global] = True

    if causal:
        layout &= np.tril(np.ones((nb, nb), dtype=bool))
    return layout


def block_sparse_mask(
    seq_len: int,
    block_size: int = 16,
    text_seq_len: int = 256,
    num_random_blocks: int | None = None,
    num_local_blocks: int = 4,
    causal: bool = True,
    seed: int = 0,
) -> np.ndarray:
    """Elementwise (seq_len, seq_len) expansion of the block layout, with the
    elementwise causal triangle applied on top."""
    layout = block_sparse_layout(
        seq_len, block_size, text_seq_len, num_random_blocks, num_local_blocks, causal, seed
    )
    dense = np.kron(layout, np.ones((block_size, block_size), dtype=bool))
    dense = dense[:seq_len, :seq_len]
    if causal:
        dense &= causal_mask(seq_len)
    return dense


def pattern_mask(attn_type: str, text_len: int, image_size: int, **kwargs) -> np.ndarray:
    """Dispatch: the static may-attend mask for a layer's attention type."""
    total = text_len + image_size**2
    if attn_type in ("full", "mlp"):
        return causal_mask(total)
    if attn_type == "axial_row":
        return axial_mask(text_len, image_size, axis=0)
    if attn_type == "axial_col":
        return axial_mask(text_len, image_size, axis=1)
    if attn_type == "conv_like":
        return conv_mask(text_len, image_size, **kwargs)
    if attn_type == "sparse":
        return block_sparse_mask(total, text_seq_len=text_len - 1, **kwargs)
    raise ValueError(f'attention type "{attn_type}" is not valid')
