"""Rotary position embeddings, TPU-native.

Re-implements (from scratch, in JAX) the rotary scheme the reference composes out
of the external ``rotary-embedding-torch`` package: 1-D language frequencies,
2-D axial "pixel" frequencies, and the DALL-E-specific 3-part head-dim split in
which text positions carry 1-D rotary angles and image positions carry 2-D
axial angles, with each modality pinned to a far-away constant position in the
other modality's coordinate system (reference: transformer.py:196-224,
attention.py:32-35).

Everything here is a pure function over static shapes: the full angle table for
a (text + image) sequence is precomputed once at model-build time and indexed
inside the compiled step, so nothing in the hot path is data-dependent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def lang_freqs(dim: int, theta: float = 10000.0) -> np.ndarray:
    """1-D rotary frequency ladder for token positions (dim//2 frequencies)."""
    return 1.0 / (theta ** (np.arange(0, dim, 2)[: dim // 2] / dim))


def pixel_freqs(dim: int, max_freq: float = 10.0) -> np.ndarray:
    """Frequencies for continuous pixel coordinates in [-1, 1]."""
    return np.linspace(1.0, max_freq / 2, dim // 2) * np.pi


def angles(positions: np.ndarray, freqs: np.ndarray) -> np.ndarray:
    """Outer product position x freq, each frequency repeated twice
    (interleaved) so the angle table lines up with adjacent rotation pairs.

    Returns shape (*positions.shape, 2 * len(freqs)).
    """
    a = np.einsum("...i,j->...ij", np.asarray(positions, dtype=np.float64), freqs)
    return np.repeat(a, 2, axis=-1).reshape(*positions.shape, -1)


@functools.lru_cache(maxsize=None)
def _rotate_half_matrix(d: int) -> np.ndarray:
    """(d, d) signed-permutation matrix P with (x @ P) = rotate_half(x)."""
    P = np.zeros((d, d), dtype=np.float32)
    idx = np.arange(0, d, 2)
    P[idx + 1, idx] = -1.0  # out[2i] = -x[2i+1]
    P[idx, idx + 1] = 1.0   # out[2i+1] = x[2i]
    return P


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    """Per adjacent pair (x1, x2) -> (-x2, x1).

    Implemented as a tiny constant signed-permutation matmul rather than a
    pair reshape/stack: each output element is exactly +-one finite input
    element (every other product is exactly 0.0), so the result matches the
    reshape formulation exactly — but the contraction runs over the
    minor-most dim on the MXU and keeps the tensor's layout, where the
    (d//2, 2) reshape forces XLA into n-minor layouts and several ms/step
    of layout-conversion copies at the flagship config. Precision.HIGHEST
    keeps f32 inputs exact (it is a no-op for bf16). Trade-off: a
    non-finite input channel (inf/nan — training already diverged) spreads
    NaN across its whole head-dim row via 0*inf, where the reshape kept it
    in its own pair."""
    assert x.shape[-1] % 2 == 0, f"rotate_half needs an even dim, got {x.shape[-1]}"
    P = jnp.asarray(_rotate_half_matrix(x.shape[-1]), x.dtype)
    return jnp.einsum("...i,ij->...j", x, P, precision=jax.lax.Precision.HIGHEST)


def apply_rotary_emb(angle_table: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
    """Rotate the leading ``angle_table.shape[-1]`` channels of ``t``.

    angle_table: (..., n, rot_dim) broadcastable to t's (..., n, d) prefix.
    Channels past rot_dim pass through untouched (the reference rotates only
    3 * (dim_head // 3 // 2 * 2) of every head's channels).
    """
    rot_dim = angle_table.shape[-1]
    angle_table = angle_table.astype(t.dtype)
    if rot_dim == t.shape[-1]:
        # full-width table (zero-padded angles rotate by identity): pure
        # elementwise — no slice/concat, so XLA emits no layout copies
        return t * jnp.cos(angle_table) + rotate_half(t) * jnp.sin(angle_table)
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    t_rot = t_rot * jnp.cos(angle_table) + rotate_half(t_rot) * jnp.sin(angle_table)
    return jnp.concatenate((t_rot, t_pass), axis=-1)


def dalle_rotary_table(
    dim_head: int,
    text_len: int,
    image_fmap_size: int,
    theta: float = 10000.0,
    max_freq: float = 10.0,
) -> np.ndarray:
    """Precompute the DALL-E rotary angle table.

    ``text_len`` counts the <bos> token (reference text_seq_len + 1); the image
    part has image_fmap_size**2 positions. Output shape is
    (text_len + image_fmap_size**2 - 1, 3 * 2 * (dim_head // 3 // 2)) — the
    trailing position is dropped because the model truncates the final token
    before the transformer (reference transformer.py:221-222).

    Layout along the channel axis, mirroring the reference scheme:
      [0, r)    : 1-D text angles; image positions pinned at position 8192
      [r, 3r)   : 2-D axial pixel angles (row then col); text pinned at -10
    where r = 2 * (dim_head // 3 // 2).
    """
    rot_dim = dim_head // 3
    img_seq_len = image_fmap_size**2

    lf = lang_freqs(rot_dim, theta)
    pf = pixel_freqs(rot_dim, max_freq)

    # 1-D text part.
    text_1d = angles(np.arange(text_len), lf)
    img_1d = angles(np.full((img_seq_len,), 8192.0), lf)
    part_text = np.concatenate((text_1d, img_1d), axis=0)

    # 2-D axial image part over a [-1, 1] pixel grid.
    axial = angles(np.linspace(-1.0, 1.0, image_fmap_size), pf)  # (f, r)
    rows = np.broadcast_to(axial[:, None, :], (image_fmap_size, image_fmap_size, axial.shape[-1]))
    cols = np.broadcast_to(axial[None, :, :], (image_fmap_size, image_fmap_size, axial.shape[-1]))
    img_2d = np.concatenate((rows, cols), axis=-1).reshape(img_seq_len, -1)
    text_2d = np.tile(angles(np.full((text_len,), -10.0), pf), (1, 2))
    part_axial = np.concatenate((text_2d, img_2d), axis=0)

    table = np.concatenate((part_text, part_axial), axis=-1)
    return table[:-1].astype(np.float32)
