"""Reversible residual execution, TPU-native.

Re-owns the reference's RevNet-style ``ReversibleSequence``
(reversible.py:54-157) as a ``jax.custom_vjp``: the forward keeps only the
final pair of residual streams; the backward reconstructs each block's inputs
from its outputs (x2 = y2 - g(y1), x1 = y1 - f(x2)) and re-runs f/g under
``jax.vjp`` — O(1) activation memory in depth, at ~2x forward compute.

Where the reference snapshots and restores CPU+CUDA RNG state to keep dropout
identical between forward and recompute (reversible.py:20-50), here each block
receives an explicit PRNG key as part of its traced inputs, so the recompute
is deterministic by construction.

Blocks are pure functions ``fn(params, x, kwargs_tree) -> (y, aux)`` where
``aux`` is a scalar side-output (the Switch MoE load-balance loss; 0.0 for
dense blocks). The sequence returns ``(out, total_aux)`` and the custom VJP
threads the aux cotangent back through every block, so MoE layers train
correctly under O(1)-memory execution — the reference's DeepSpeed analog
cannot combine MoE with activation checkpointing of this kind at all.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

BlockFn = Callable[[Any, jnp.ndarray, Any], Tuple[jnp.ndarray, jnp.ndarray]]


def _split(x):
    return jnp.split(x, 2, axis=-1)


def _run_blocks(fns, params, x, kwargs):
    """The shared reversible wiring: y1 = x1 + f(x2), y2 = x2 + g(y1),
    accumulating each block's scalar aux side-output."""
    x1, x2 = _split(x)
    aux = jnp.zeros((), jnp.float32)
    for (f, g), (pf, pg), (kwf, kwg) in zip(fns, params, kwargs):
        df, af = f(pf, x2, kwf)
        x1 = x1 + df
        dg, ag = g(pg, x1, kwg)
        x2 = x2 + dg
        aux = aux + af + ag
    return jnp.concatenate((x1, x2), axis=-1), aux


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def reversible_sequence(
    fns: Tuple[Tuple[BlockFn, BlockFn], ...],
    params: Sequence[Tuple[Any, Any]],
    x: jnp.ndarray,
    kwargs: Sequence[Tuple[Any, Any]],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run ``x -> [x1; x2]`` through reversible blocks; input x is
    (b, n, 2d). Returns (output, summed aux side-outputs)."""
    return _run_blocks(fns, params, x, kwargs)


def _fwd(fns, params, x, kwargs):
    y, aux = reversible_sequence(fns, params, x, kwargs)
    return (y, aux), (params, y, kwargs)


def _bwd(fns, res, cts):
    params, y, kwargs = res
    dy, daux = cts
    y1, y2 = _split(y)
    dy1, dy2 = _split(dy)

    dparams_rev, dkwargs_rev = [], []
    for (f, g), (pf, pg), (kwf, kwg) in zip(
        reversed(fns), reversed(list(params)), reversed(list(kwargs))
    ):
        (g_out, _), g_vjp = jax.vjp(g, pg, y1, kwg)
        x2 = y2 - g_out
        dpg, dy1_from_g, dkwg = g_vjp((dy2, daux))
        dy1 = dy1 + dy1_from_g

        (f_out, _), f_vjp = jax.vjp(f, pf, x2, kwf)
        x1 = y1 - f_out
        dpf, dx2_from_f, dkwf = f_vjp((dy1, daux))
        dy2 = dy2 + dx2_from_f

        y1, y2 = x1, x2
        dparams_rev.append((dpf, dpg))
        dkwargs_rev.append((dkwf, dkwg))

    dx = jnp.concatenate((dy1, dy2), axis=-1)
    return list(reversed(dparams_rev)), dx, list(reversed(dkwargs_rev))


reversible_sequence.defvjp(_fwd, _bwd)


def reversible_forward_only(fns, params, x, kwargs):
    """The same wiring without the custom VJP — for eval / decode paths where
    no gradient flows and XLA may fuse freely. Returns (out, total_aux)."""
    return _run_blocks(fns, params, x, kwargs)
