"""Mixture-of-experts feed-forward with expert parallelism.

The reference has no MoE (SURVEY.md §2.2 lists EP as n/a); this is a
beyond-parity scaling axis in the GShard/Switch lineage, built so GSPMD can
shard it over the ``ep`` mesh axis with zero manual collectives:

- Switch-style top-1 routing: a linear gate scores experts per token; each
  token goes to its argmax expert, weighted by the gate probability
  (straight-through for the dropped experts' gradient via the prob weight);
- capacity-based dispatch: each expert processes at most
  ``capacity_factor * tokens / num_experts`` tokens per example; overflow
  tokens fall through the residual (standard Switch behavior). Dispatch and
  combine are one-hot einsums over a (tokens, experts, capacity) tensor —
  the mesh-tensorflow formulation whose expert dimension GSPMD shards over
  ``ep``, turning the einsums into all_to_all exchanges on ICI;
- a load-balance auxiliary loss (mean routed fraction x mean gate prob per
  expert, scaled by E — Switch eq. 4) is written to the mutable ``moe_aux``
  collection; trainers add ``moe_aux_weight * sum(aux)`` to the objective
  (train_dalle.py does when --moe_experts > 0);
- expert weights are (E, ...) leaves; parallel/sharding.py's rules place
  them as P("ep", ...), so each device stores and computes only its
  experts.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

Dtype = Any


class MoEFeedForward(nn.Module):
    """Switch-routed GEGLU feed-forward over ``num_experts`` experts."""

    dim: int
    num_experts: int
    mult: float = 4.0
    capacity_factor: float = 1.25
    dropout: float = 0.0
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        b, n, d = x.shape
        e = self.num_experts
        hidden = int(self.dim * self.mult)
        cap = max(int(self.capacity_factor * n / e), 1)

        gate_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32,
            param_dtype=self.param_dtype, name="gate",
        )(x.astype(jnp.float32))  # (b, n, e) — routing in f32 for stability
        probs = jax.nn.softmax(gate_logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)  # (b, n)
        expert_prob = jnp.take_along_axis(probs, expert_idx[..., None], axis=-1)[..., 0]

        # position of each token within its expert's capacity buffer:
        # running count of same-expert tokens before it (scan order = seq)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # (b, n, e)
        position = jnp.cumsum(onehot, axis=1) * onehot  # 1-based where routed
        position = jnp.sum(position, axis=-1) - 1  # (b, n), -1 never happens
        keep = position < cap  # overflow tokens fall through

        # load-balance aux (Switch eq. 4): E * sum_e f_e * P_e
        frac = jnp.mean(onehot.astype(jnp.float32), axis=(0, 1))  # (e,)
        prob_mean = jnp.mean(probs, axis=(0, 1))
        aux = e * jnp.sum(frac * prob_mean)
        self.sow("moe_aux", "load_balance", aux)

        # dispatch: (b, n, e, cap) one-hot; combine re-weights by gate prob
        pos_oh = jax.nn.one_hot(
            jnp.where(keep, position, cap), cap, dtype=x.dtype
        )  # (b, n, cap); out-of-capacity rows are all-zero
        dispatch = onehot.astype(x.dtype)[..., None] * pos_oh[:, :, None, :]
        combine = dispatch * expert_prob[..., None, None].astype(x.dtype)

        xs = jnp.einsum("bnec,bnd->ebcd", dispatch, x.astype(self.dtype))

        w_in = self.param(
            "experts_in", nn.initializers.lecun_normal(),
            (e, d, hidden * 2), self.param_dtype,
        )
        w_out = self.param(
            "experts_out", nn.initializers.lecun_normal(),
            (e, hidden, d), self.param_dtype,
        )
        h = jnp.einsum(
            "ebcd,edh->ebch", xs, w_in.astype(self.dtype)
        )
        h, gates = jnp.split(h, 2, axis=-1)
        h = h * jax.nn.gelu(gates)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        ys = jnp.einsum("ebch,ehd->ebcd", h, w_out.astype(self.dtype))

        return jnp.einsum("bnec,ebcd->bnd", combine, ys)
