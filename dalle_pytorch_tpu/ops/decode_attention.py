"""Fused single-token decode attention (Pallas TPU kernel) — implemented,
measured, and OFF by default.

The hypothesis: batch-1 decode is latency-bound on the ~12 small XLA ops
between the qkv projection and the output projection, so collapsing them
into one kernel should save their per-op overhead. The measurement
(v5e-1, flagship config): the kernel costs ~29 us/layer in isolation while
the XLA op chain it replaces runs in ~10 us/layer — XLA's fusion pipeline
already collapses the chain well, and the kernel's skinny per-head MXU
matvecs serialize across the 8 head-group programs. End to end the kernel
REGRESSED generation 0.999 -> 1.36 ms/token, so the dispatch in
ops/attention.py is gated on ``FUSED_DECODE_ENABLED`` (env
``DALLE_TPU_FUSED_DECODE=1``), default off. It stays in the tree as a
correct, tested alternative (and a recorded negative result: the same
conclusion as the int8 KV cache — see ops/attention.py — decode here is
bound by weight streaming, not by the attention op chain).

The kernel fuses, per layer:

    rotary(q, k, v)  ->  scores = q K_cache^T (+ the new token's own k)  ->
    causal + key-padding mask  ->  softmax  ->  out = attn [V_cache; v]

- the packed (b, 1, 3 h d) qkv row streams straight from the projection
  (the same three-views-of-one-operand trick as the fused training kernel);
- the K/V caches are READ-ONLY inputs: the current position's contribution
  enters the softmax directly from the just-rotated k/v (its cache row is
  stale), so the kernel never writes the caches — Mosaic cannot store to a
  dynamic sublane row, and an aliased full-block write-back would cost a
  full cache sweep of HBM writes per step. The rotated k/v rows are emitted
  as side outputs and written into the caches by two one-row
  dynamic_update_slices in XLA (in-place on the donated decode state);
- rotary cos/sin rows for position ``idx`` arrive via scalar-prefetch
  index maps (the position picks the block, no in-kernel gather); rotation
  applies to q, k AND v — the DALL-E quirk (reference attention.py:75-78);
- the causal mask is an iota-vs-idx compare (STRICT: the stale cache row at
  idx is excluded; the fresh token adds itself explicitly); the optional
  runtime key-padding mask streams as a pre-transposed (b, L, 1) operand;
- grid (b, h / hpb): each program handles one head group (hpb = 128 / d
  heads) so the lane dimension stays full.

Semantics match ops/attention.py:_decode_attend for attn_type="full",
causal, single-token steps (pinned by tests/test_decode_kernel.py); other
pattern types and multi-token prefill keep the unfused path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .jax_compat import tpu_compiler_params

NEG_INF = -1e30

# opt-in dispatch (see module docstring): flip via env or monkeypatch
import os

FUSED_DECODE_ENABLED = os.environ.get("DALLE_TPU_FUSED_DECODE", "0") == "1"


def fused_decode_supported(heads: int, dim_head: int) -> bool:
    """One source of truth for the kernel's head-group constraint (the
    dispatch guard in ops/attention.py and the kernel assert both use it):
    lanes must tile into whole heads and heads into whole groups."""
    return 128 % dim_head == 0 and heads % max(1, 128 // dim_head) == 0


def _kernel(
    idx_ref,  # (1,) scalar prefetch: current position
    q_ref, k_new_ref, v_new_ref,  # (1, 1, hpb*d) views of the packed qkv row
    cos_ref, sin_ref,             # (1, 1, hpb*d) rotary rows for position idx
    p_ref,                        # (d, d) rotate-half matrix
    kmask_ref,                    # (1, L, 1) int32 key mask or None
    kcache_ref, vcache_ref,       # (1, L, hpb*d) read-only caches
    o_ref, k_out_ref, v_out_ref,  # (1, 1, hpb*d) outputs
    *, d: int, hpb: int, L: int, scale: float, use_rotary: bool,
):
    idx = idx_ref[0]
    q = q_ref[0].astype(jnp.float32)        # (1, hpb*d)
    k = k_new_ref[0].astype(jnp.float32)
    v = v_new_ref[0].astype(jnp.float32)

    if use_rotary:
        cos = cos_ref[0].astype(jnp.float32)  # (1, hpb*d)
        sin = sin_ref[0].astype(jnp.float32)
        P = p_ref[:].astype(jnp.float32)      # (d, d)

        def rot(t):
            halves = []
            for hi in range(hpb):
                th = t[:, hi * d:(hi + 1) * d]
                rotated = jax.lax.dot_general(
                    th, P, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                halves.append(
                    th * cos[:, hi * d:(hi + 1) * d]
                    + rotated * sin[:, hi * d:(hi + 1) * d]
                )
            return jnp.concatenate(halves, axis=-1)

        q, k, v = rot(q), rot(k), rot(v)

    # the new row reaches the softmax in the caches' dtype — exactly the
    # values the XLA-side row write will store, so fused steps are
    # bit-consistent with later reads of the cache
    k_store = k.astype(k_out_ref.dtype)
    v_store = v.astype(v_out_ref.dtype)
    k_out_ref[0] = k_store
    v_out_ref[0] = v_store
    kq = k_store.astype(jnp.float32)
    vq = v_store.astype(jnp.float32)

    K = kcache_ref[0].astype(jnp.float32)   # (L, hpb*d)
    V = vcache_ref[0].astype(jnp.float32)
    # STRICT past-only mask: the cache row at idx is stale; the fresh
    # token's contribution is added explicitly below
    rows = jax.lax.broadcasted_iota(jnp.int32, (L, 1), 0)
    live_rows = rows < idx
    new_live = jnp.float32(1.0)
    if kmask_ref is not None:
        km = kmask_ref[0] > 0
        live_rows = jnp.logical_and(live_rows, km)
        # the key-padding mask also applies to the current position's own
        # key (matching the unfused path's allowed &= mask)
        new_live = jnp.max(
            jnp.where(jnp.logical_and(rows == idx, km), 1.0, 0.0)
        )

    # both sweeps run as MXU dots (cross-lane VPU reductions are an order
    # of magnitude slower than a skinny matmul here)
    qs = q * scale
    outs = []
    for hi in range(hpb):
        sl = slice(hi * d, (hi + 1) * d)
        s = jax.lax.dot_general(  # (L, d) x (1, d) -> (L, 1)
            K[:, sl], qs[:, sl], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        s = jnp.where(live_rows, s, NEG_INF)
        s_new = jnp.sum(kq[:, sl] * qs[:, sl])                     # scalar
        # a key-padding-masked current token must not poison the softmax
        # max: its raw score could exceed every live score by enough to
        # underflow them all (making the output spuriously zero)
        s_new = jnp.where(new_live > 0, s_new, NEG_INF)
        m = jnp.maximum(jnp.max(s), s_new)
        p = jnp.where(live_rows, jnp.exp(s - m), 0.0)              # (L, 1)
        p_new = jnp.exp(s_new - m) * new_live
        l = jnp.sum(p) + p_new
        # every-key-masked rows emit 0 (the flash-kernel convention; the
        # dense path's uniform-average is unreachable in decode — <bos> is
        # always a live key)
        l = jnp.where(l == 0.0, 1.0, l)
        acc = jax.lax.dot_general(  # (1, L) x (L, d) -> (1, d)
            p.reshape(1, L), V[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        outs.append((acc + p_new * vq[:, sl]) / l)
    o_ref[0] = jnp.concatenate(outs, axis=-1).astype(o_ref.dtype)


def _kernel_nomask(idx_ref, q_ref, k_new_ref, v_new_ref, cos_ref, sin_ref,
                   p_ref, kcache_ref, vcache_ref,
                   o_ref, k_out_ref, v_out_ref, **kw):
    _kernel(idx_ref, q_ref, k_new_ref, v_new_ref, cos_ref, sin_ref, p_ref,
            None, kcache_ref, vcache_ref, o_ref, k_out_ref, v_out_ref, **kw)


@functools.partial(
    jax.jit,
    static_argnames=("heads", "dim_head", "use_rotary", "interpret"),
)
def fused_decode_attention(
    qkv: jnp.ndarray,         # (b, 1, 3*h*d) packed projection output
    k_cache: jnp.ndarray,     # (b, L, h*d) — read-only here
    v_cache: jnp.ndarray,     # (b, L, h*d)
    idx: jnp.ndarray,         # scalar int32
    cos: jnp.ndarray,         # (T, d) rotary cos table (ignored w/o rotary)
    sin: jnp.ndarray,
    rot_p: jnp.ndarray,       # (d, d) rotate-half matrix
    key_mask: Optional[jnp.ndarray],  # (b, L, 1) int32 or None
    *, heads: int, dim_head: int, use_rotary: bool, interpret: bool = False,
):
    """-> (out, k_row, v_row), each (b, 1, h*d); the caller writes
    k_row/v_row into the caches at ``idx`` (one-row updates in XLA)."""
    b, L, hd = k_cache.shape
    d, h = dim_head, heads
    assert hd == h * d, (k_cache.shape, heads, dim_head)
    assert fused_decode_supported(h, d), (h, d)
    hpb = max(1, 128 // d)
    groups = h // hpb

    idx_arr = jnp.asarray(idx, jnp.int32).reshape(1)

    # index maps under PrefetchScalarGridSpec receive the scalar-prefetch
    # ref LAST: (grid..., scalars)
    qkv_spec = lambda off: pl.BlockSpec(
        (1, 1, hpb * d), lambda b_, g, s: (b_, 0, off * groups + g)
    )
    # rotary rows for position idx: per-head-dim table rows are identical
    # across heads, tile to the group width once at trace time (static).
    # The (T, 1, hpb*d) layout keeps the block's trailing dims equal to the
    # array's (Mosaic requires (8, 128)-divisible or full-dimension blocks);
    # the table may be shorter than the cache (the final position never
    # decodes — it predicts nothing — so its row is never fetched)
    T = cos.shape[0]
    cos_g = jnp.tile(cos, (1, hpb)).reshape(T, 1, hpb * d)
    sin_g = jnp.tile(sin, (1, hpb)).reshape(T, 1, hpb * d)
    row_spec = pl.BlockSpec((1, 1, hpb * d), lambda b_, g, s: (s[0], 0, 0))

    in_specs = [
        qkv_spec(0), qkv_spec(1), qkv_spec(2),
        row_spec, row_spec,
        pl.BlockSpec((d, d), lambda b_, g, s: (0, 0)),
    ]
    operands = [qkv, qkv, qkv, cos_g, sin_g, rot_p]
    if key_mask is not None:
        in_specs.append(pl.BlockSpec((1, L, 1), lambda b_, g, s: (b_, 0, 0)))
        operands.append(key_mask)
    cache_spec = pl.BlockSpec((1, L, hpb * d), lambda b_, g, s: (b_, 0, g))
    in_specs += [cache_spec, cache_spec]
    operands += [k_cache, v_cache]

    kernel = functools.partial(
        _kernel if key_mask is not None else _kernel_nomask,
        d=d, hpb=hpb, L=L, scale=d**-0.5, use_rotary=use_rotary,
    )

    row_out = pl.BlockSpec((1, 1, hpb * d), lambda b_, g, s: (b_, 0, g))
    out, k_row, v_row = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, groups),
            in_specs=in_specs,
            out_specs=[row_out, row_out, row_out],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, 1, h * d), qkv.dtype),
            jax.ShapeDtypeStruct((b, 1, h * d), k_cache.dtype),
            jax.ShapeDtypeStruct((b, 1, h * d), v_cache.dtype),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(idx_arr, *operands)
    return out, k_row, v_row
