"""Core layer primitives (flax.linen), TPU-native.

Covers the reference's transformer building blocks (transformer.py:30-126):
DivideMax, LayerScale, PreNorm, GEGLU feed-forward, and the CogView-style
token-shift wrapper. All modules take explicit compute/param dtypes so the
whole stack can run bf16 on the MXU with f32 parameters.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

Dtype = Any


def stable_softmax(t: jnp.ndarray, axis: int = -1, alpha: float = 32.0**2) -> jnp.ndarray:
    """Numerically-tamed softmax used when ``stable=True``
    (reference attention.py:27-30): divide by alpha before the max-subtraction
    so large logits don't overflow in low precision."""
    t = t / alpha
    t = t - jnp.max(t, axis=axis, keepdims=True)
    return nn.softmax(t * alpha, axis=axis)


def divide_max(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Divide by the per-slice max (reference transformer.py:30-37)."""
    return x / jnp.max(x, axis=axis, keepdims=True)


def layer_scale_init(depth: int) -> float:
    """Depth-dependent LayerScale init (reference transformer.py:40-48):
    0.1 up to depth 18, 1e-5 to 24, 1e-6 beyond."""
    if depth <= 18:
        return 0.1
    if depth <= 24:
        return 1e-5
    return 1e-6


class LayerScale(nn.Module):
    """Scale a wrapped function's output by a learned per-channel gain
    initialised small (CaiT, arXiv:2103.17239; reference transformer.py:40-54)."""

    dim: int
    depth: int
    fn: nn.Module
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, **kwargs):
        init = layer_scale_init(self.depth)
        scale = self.param(
            "scale",
            lambda key, shape: jnp.full(shape, init, dtype=self.param_dtype),
            (self.dim,),
        )
        return self.fn(x, **kwargs) * scale.astype(x.dtype)


class PreNorm(nn.Module):
    """LayerNorm then fn (reference transformer.py:58-65). The norm runs in
    f32 for stability regardless of compute dtype."""

    dim: int
    fn: nn.Module
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, **kwargs):
        y = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype)(x)
        return self.fn(y.astype(x.dtype), **kwargs)


class QuantDense(nn.Module):
    """Weight-only int8 Dense for serving: ``y = (x @ q) * scale [+ bias]``
    with a per-output-channel symmetric scale.

    Autoregressive decode is bound by weight reads from HBM (every step
    streams every kernel once); int8 storage halves those bytes vs bf16
    (measured 1.05 -> 0.85 ms/token on the flagship config, v5e-1). The
    ``q.astype`` dequant fuses into the consuming matvec loop fusion, so
    the kernel is read from HBM as int8 and widened in registers. Params are
    produced by ``utils/quantize.py`` from a trained checkpoint — training
    through this module is unsupported (int8 params receive no meaningful
    gradients)."""

    features: int
    use_bias: bool = True
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        params = {
            "kernel_q": self.param(
                "kernel_q",
                lambda key, shape: jnp.zeros(shape, jnp.int8),
                (in_features, self.features),
            ),
            "scale": self.param(
                "scale",
                lambda key, shape: jnp.ones(shape, jnp.float32),
                (self.features,),
            ),
        }
        if self.use_bias:
            params["bias"] = self.param(
                "bias",
                lambda key, shape: jnp.zeros(shape, self.param_dtype),
                (self.features,),
            )
        # the full matvec IS the all-columns slice: one implementation
        # (``dense_apply_columns``) serves this module and the sliced image
        # head (models/dalle.py:_head_image), so the two cannot diverge
        return dense_apply_columns(params, x, 0, self.dtype)


def dense_apply_columns(params, x: jnp.ndarray, lo: int, dtype) -> jnp.ndarray:
    """The ``[lo:]`` output-column slice of a (Quant)Dense matvec, computed
    from the module's raw param dict — the ONE place the sliced-head
    arithmetic lives, shared between ``QuantDense.__call__``'s math and
    column-sliced consumers (models/dalle.py:_head_image). Handles both the
    int8 serving params ({kernel_q, scale}) and the full-precision
    ({kernel}) layout, bias included when present; the slice of the matvec
    is exact (column j of ``x @ W + b`` depends only on column j of W/b),
    so streaming fewer weight bytes never changes the kept outputs."""
    x = x.astype(dtype)
    if "kernel_q" in params:
        # QuantDense: int8 columns widened in-register, then the
        # per-output-channel scale
        q = jnp.asarray(params["kernel_q"])[:, lo:]
        y = (x @ q.astype(dtype)) * jnp.asarray(params["scale"])[lo:].astype(dtype)
    else:
        y = x @ jnp.asarray(params["kernel"], dtype)[:, lo:]
    if "bias" in params:
        y = y + jnp.asarray(params["bias"])[lo:].astype(dtype)
    return y


class QuantEmbed(nn.Module):
    """int8 embedding table for serving: rows are stored int8 with a
    per-row symmetric scale and dequantized after the gather, so the table
    reads from HBM at half the bf16 bytes. Params come from
    ``utils/quantize.py`` (training through this module is unsupported)."""

    num_embeddings: int
    features: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids):
        q = self.param(
            "embedding_q",
            lambda key, shape: jnp.zeros(shape, jnp.int8),
            (self.num_embeddings, self.features),
        )
        scale = self.param(
            "scale",
            lambda key, shape: jnp.ones(shape, jnp.float32),
            (self.num_embeddings,),
        )
        rows = jnp.take(q, ids, axis=0).astype(self.dtype)
        s = jnp.take(scale, ids, axis=0).astype(self.dtype)
        return rows * s[..., None]


def serving_embed(
    quant: bool,
    num_embeddings: int,
    features: int,
    *,
    name: Optional[str] = None,
    dtype: Dtype = jnp.float32,
    param_dtype: Dtype = jnp.float32,
) -> nn.Module:
    """``nn.Embed`` vs int8 ``QuantEmbed`` — the embedding analog of
    ``serving_dense`` (same structural-parallelism contract). param_dtype
    governs only the trainable table; the int8 twin's dtypes are fixed
    (int8 rows, f32 scales)."""
    if quant:
        return QuantEmbed(num_embeddings, features, name=name, dtype=dtype)
    return nn.Embed(num_embeddings, features, name=name, param_dtype=param_dtype)


def serving_dense(
    quant: bool,
    features: int,
    *,
    use_bias: bool = True,
    name: Optional[str] = None,
    dtype: Dtype = jnp.float32,
    param_dtype: Dtype = jnp.float32,
) -> nn.Module:
    """The one place that picks ``nn.Dense`` vs int8 ``QuantDense`` for a
    projection — every Dense-bearing module routes through it so the
    quantized and full-precision trees stay structurally parallel."""
    if quant:
        return QuantDense(
            features, use_bias=use_bias, name=name,
            dtype=dtype, param_dtype=param_dtype,
        )
    return nn.Dense(
        features, use_bias=use_bias, name=name,
        dtype=dtype, param_dtype=param_dtype,
    )


class FeedForward(nn.Module):
    """GEGLU feed-forward (reference transformer.py:69-85): one fused
    projection to 2 * mult * dim, gated gelu, projection back. The doubled
    projection keeps the MXU fed with one large matmul instead of two.
    ``quant=True`` swaps both projections for int8 ``QuantDense`` (serving
    only; see utils/quantize.py)."""

    dim: int
    mult: float = 4.0
    dropout: float = 0.0
    quant: bool = False
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        hidden = int(self.dim * self.mult)
        dense = lambda features: serving_dense(
            self.quant, features, dtype=self.dtype, param_dtype=self.param_dtype
        )
        x = dense(hidden * 2)(x)
        x, gates = jnp.split(x, 2, axis=-1)
        x = x * nn.gelu(gates)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        x = dense(self.dim)(x)
        return x


def shift_tokens(x: jnp.ndarray, text_len: int, image_size: int) -> jnp.ndarray:
    """CogView/RWKV token shift over a mixed text+image sequence
    (reference transformer.py:96-126).

    Text tokens (first ``text_len`` positions, <bos> included): the first half
    of channels is replaced by the previous token's. Image tokens (reshaped to
    an image_size x image_size grid, zero-padded to a full grid): the first
    quarter of channels comes from the token one row up, the second quarter
    from the token one column left.

    Static-shape: works on the full sequence; callers pass the model's fixed
    sequence length.
    """
    b, n, d = x.shape
    img_seq_len = image_size**2
    padding = text_len + img_seq_len - n

    x_text, x_img = x[:, :text_len], x[:, text_len:]
    x_img = jnp.pad(x_img, ((0, 0), (0, padding), (0, 0)))
    x_img = x_img.reshape(b, image_size, image_size, d)

    # text: shift half the channels right by one token
    x_text_shift, x_text_pass = jnp.split(x_text, 2, axis=-1)
    x_text_shift = jnp.pad(x_text_shift, ((0, 0), (1, 0), (0, 0)))[:, :-1]
    x_text = jnp.concatenate((x_text_shift, x_text_pass), axis=-1)

    # image: quarter from the row above, quarter from the column left
    q = d // 4
    top, left, passthrough = x_img[..., :q], x_img[..., q : 2 * q], x_img[..., 2 * q :]
    top = jnp.pad(top, ((0, 0), (1, 0), (0, 0), (0, 0)))[:, :-1]
    left = jnp.pad(left, ((0, 0), (0, 0), (1, 0), (0, 0)))[:, :, :-1]
    x_img = jnp.concatenate((top, left, passthrough), axis=-1)

    x_img = x_img.reshape(b, img_seq_len, d)
    if padding:
        x_img = x_img[:, :-padding]
    return jnp.concatenate((x_text, x_img), axis=1)


class PreShiftToken(nn.Module):
    """Apply token shift, then the wrapped function
    (reference transformer.py:89-126).

    In decode mode a history cache of raw inputs supplies the previous-token
    and row-above features the shift needs, so KV-cached sampling stays O(1)
    per step. ``pass_decode`` controls whether the wrapped fn also receives
    the decode flag (attention does, feed-forward doesn't).

    ``pad`` widens the ring by that many EXTRA rows of history — the
    speculative-decode rollback slack (serving/engine.py): a verify block
    of k tokens advances the ring by k, but only ``accepted <= k``
    positions survive, so the next block's descriptor ``block_start`` may
    LAG the stored high-water mark by up to ``pad`` positions and every
    read it needs (prev token, row-above) must still be resident. With
    ``pad=0`` (every non-speculative model) the ring is exactly the
    original ``image_size + 1`` rows and the anchored index arithmetic
    below reduces to the unanchored offsets bit-for-bit.
    """

    fn: nn.Module
    image_size: int
    seq_len: int
    pass_decode: bool = False
    pad: int = 0

    @nn.compact
    def __call__(self, x, decode: bool = False, block_len=None,
                 block_start=None, **kwargs):
        img_seq_len = self.image_size**2
        text_len = self.seq_len - img_seq_len + 1
        inner_kwargs = dict(kwargs)
        if self.pass_decode:
            inner_kwargs["decode"] = decode
            if block_len is not None:
                inner_kwargs["block_len"] = block_len
            if block_start is not None:
                inner_kwargs["block_start"] = block_start

        if not decode:
            x = shift_tokens(x, text_len, self.image_size)
            return self.fn(x, **inner_kwargs)

        b, n, d = x.shape
        # The shift only ever looks back image_size positions (prev token and
        # row-above), so the history is a RING of the last R = image_size + 1
        # raw inputs, newest last: before consuming position pos, row j holds
        # position pos - R + j. A full-sequence (b, total, d) history was the
        # original design; its per-step updates were part of a
        # dynamic-update-slice category trace-measured at 43% of the
        # batch-8 decode program (shared with the K/V cache updates — see
        # ops/attention.py's cost notes for the split and the KV-side fix).
        # The ring is ~40x smaller, uses only STATIC slice indices, and is
        # bit-identical — every read the ring
        # cannot serve (pos 0's "previous", out-of-grid row-above) is already
        # masked to zero inside shift_tokens_decode / the prefill rule.
        R = self.image_size + 1 + self.pad
        is_init = not self.has_variable("cache", "shift_hist")
        hist = self.variable("cache", "shift_hist", jnp.zeros, (b, R, d), x.dtype)
        pos_var = self.variable("cache", "shift_index", lambda: jnp.array(0, jnp.int32))
        if is_init:
            return self.fn(x, **inner_kwargs)

        pos = pos_var.value
        if block_len is not None:
            # RAGGED block (the fused serving iteration): row b's valid
            # tokens are columns [0, block_len[b]) at positions
            # anchor[b] + j, mixing text (prefill rows) and image (decode
            # rows) — the per-position decode rules apply elementwise.
            # ``cat`` maps any position anchor[b] + t (t in [-R, n)) to
            # column R + t: prev is position p-1 (column R+j-1), the
            # row-above token p - image_size (column R+j-image_size;
            # R >= image_size + 1 keeps both indices >= 0). The ring then
            # advances PER ROW by block_len — a pure gather, bitwise
            # equal to the split paths' concatenate update at the same
            # advance (idle rows advance 0 and keep their ring intact).
            #
            # ``block_start`` anchors the block at the DESCRIPTOR's
            # position instead of the stored high-water mark: after a
            # speculative verify commits only ``accepted`` of its
            # block_len tokens (serving/engine.py), the next descriptor
            # lags the stored index by delta = pos - block_start, and
            # every ring read below the anchor shifts down by delta —
            # the per-row cache rewind, realized as index arithmetic on
            # the (pad-widened) ring rather than a device round trip.
            # The rows the over-advance polluted (positions >= anchor)
            # are never read from the ring: in-block positions gather
            # from ``x`` itself. With block_start == pos (every
            # non-speculative dispatch) delta is 0 and every index
            # below equals the unanchored form.
            assert jnp.ndim(pos) == 1, (
                "ragged blocks need a vectorized (b,) shift index "
                "(models/sampling.py:set_decode_offsets)"
            )
            jidx = jnp.arange(n, dtype=jnp.int32)
            cat = jnp.concatenate((hist.value, x), axis=1)  # (b, R+n, d)
            if block_start is None:
                anchor = pos
                delta = jnp.zeros_like(pos)
            else:
                anchor = block_start
                # idle rows (block_len 0) carry garbage descriptors; pin
                # them to delta 0 so their ring state passes through
                delta = jnp.where(
                    block_len > 0, jnp.maximum(pos - block_start, 0), 0
                )
            prev_ix = jnp.where(
                jidx[None] == 0, R - 1 - delta[:, None], R - 1 + jidx[None]
            )
            prev = jnp.take_along_axis(cat, prev_ix[..., None], axis=1)
            above_ix = (
                R - self.image_size + jidx[None]
                - jnp.where(jidx[None] >= self.image_size, 0, 1)
                * delta[:, None]
            )
            row_above = jnp.take_along_axis(
                cat, jnp.clip(above_ix, 0, R + n - 1)[..., None], axis=1
            )
            pos_bj = anchor[:, None] + jidx[None]           # (b, n)
            take = (
                jnp.arange(R, dtype=jnp.int32)[None] + block_len[:, None]
                - jnp.where(
                    jnp.arange(R, dtype=jnp.int32)[None]
                    >= R - block_len[:, None],
                    0, 1,
                ) * delta[:, None]
            )
            take = jnp.clip(take, 0, R + n - 1)
            hist.value = jnp.take_along_axis(cat, take[..., None], axis=1)
            pos_var.value = jnp.where(
                block_len > 0, anchor + block_len, pos
            )
            x = shift_tokens_decode(
                x, pos_bj, prev, row_above, text_len, self.image_size
            )
        elif n > 1:
            # prefill: a block of n text positions (n <= text_len and the
            # whole block must lie inside the text part — callers prefill the
            # prompt; pos is traced so this cannot be asserted). Only the
            # text rule applies: first half of channels from the previous
            # token — block-internal rows shift from the block itself, row 0
            # from the history (zero when the block starts the sequence).
            assert n <= text_len, "prefill blocks must stay within the text part"
            prev_first = jnp.where(pos > 0, hist.value[:, -1:], 0.0)
            prev_block = jnp.concatenate((prev_first, x[:, :-1]), axis=1)
            pos_var.value = pos + n
            hist.value = (
                x[:, n - R :]
                if n >= R
                else jnp.concatenate((hist.value[:, n:], x), axis=1)
            )
            half = d // 2
            x = jnp.concatenate((prev_block[..., :half], x[..., half:]), axis=-1)
        else:
            prev = hist.value[:, R - 1 :]  # position pos - 1
            # position pos - image_size: ring row R - image_size (== 1
            # for the unpadded ring)
            ra = R - self.image_size
            row_above = hist.value[:, ra : ra + 1]
            pos_var.value = pos + 1
            hist.value = jnp.concatenate((hist.value[:, 1:], x), axis=1)
            x = shift_tokens_decode(x, pos, prev, row_above, text_len, self.image_size)
        return self.fn(x, **inner_kwargs)


class AxialPositionalEmbedding(nn.Module):
    """Factorized 2-D learned position embedding over the image grid.

    Re-owns the external ``axial_positional_embedding`` package the reference
    pulls in (dalle_pytorch.py:7,343-344): one (rows, dim) and one (cols, dim)
    parameter whose broadcast sum covers the full grid — O(2·f·d) parameters
    instead of O(f²·d).
    """

    dim: int
    shape: tuple  # (rows, cols)
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, n: int):
        """Return the first ``n`` grid position embeddings, shape (1, n, dim)
        in param dtype (n <= rows * cols)."""
        rows, cols = self.shape
        row_emb = self.param(
            "row_emb", nn.initializers.normal(1.0), (rows, 1, self.dim), self.param_dtype
        )
        col_emb = self.param(
            "col_emb", nn.initializers.normal(1.0), (1, cols, self.dim), self.param_dtype
        )
        grid = (row_emb + col_emb).reshape(rows * cols, self.dim)
        return grid[None, :n]


class SpatialGatingUnit(nn.Module):
    """gMLP spatial gating (arXiv:2105.08050; the reference pulls this in from
    the external g-mlp-pytorch package for attn_type='mlp',
    transformer.py:13,170-178): half the channels gate the other half through
    a learned, optionally causal, seq x seq spatial mixing matrix."""

    seq_len: int
    causal: bool = True
    init_eps: float = 1e-3
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, decode: bool = False):
        n = x.shape[-2]
        res, gate = jnp.split(x, 2, axis=-1)
        gate = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype)(gate)
        gate = gate.astype(x.dtype)

        eps = self.init_eps / self.seq_len
        weight = self.param(
            "spatial_weight",
            nn.initializers.uniform(scale=2 * eps),
            (self.seq_len, self.seq_len),
            self.param_dtype,
        ) - eps
        bias = self.param(
            "spatial_bias", nn.initializers.ones, (self.seq_len,), self.param_dtype
        )

        if decode:
            return self._decode_gate(x, res, gate, weight, bias)

        w = weight[:n, :n]
        if self.causal:
            w = jnp.where(jnp.tril(jnp.ones((n, n), dtype=bool)), w, 0.0)
        gate = jnp.einsum("bnd,mn->bmd", gate, w.astype(x.dtype))
        gate = gate + bias[:n, None].astype(x.dtype)
        return res * gate

    def _decode_gate(self, x, res, gate, weight, bias):
        """Decode against the gate-history cache: the gate mixes over the full
        (normalized) gate history — without the cache, a 1-token input would
        see only w[:1, :1] instead of its history row and sampling with 'mlp'
        layers would silently produce garbage. Handles single-token steps and
        multi-token prefill blocks (n > 1) alike."""
        b, n, dh = gate.shape
        is_init = not self.has_variable("cache", "gate_hist")
        hist = self.variable(
            "cache", "gate_hist", jnp.zeros, (b, self.seq_len, dh), gate.dtype
        )
        idx_var = self.variable(
            "cache", "gate_index", lambda: jnp.array(0, jnp.int32)
        )
        if is_init:
            return res * gate

        idx = idx_var.value
        hist.value = jax.lax.dynamic_update_slice(hist.value, gate, (0, idx, 0))
        w_rows = jax.lax.dynamic_slice(weight, (idx, 0), (n, self.seq_len))
        if self.causal:
            cols = jnp.arange(self.seq_len)
            rows = idx + jnp.arange(n)
            w_rows = jnp.where(cols[None, :] <= rows[:, None], w_rows, 0.0)
        out = jnp.einsum("bnd,mn->bmd", hist.value, w_rows.astype(x.dtype))
        out = out + jax.lax.dynamic_slice(bias, (idx,), (n,))[:, None].astype(x.dtype)
        idx_var.value = idx + n
        return res * out


class GMLPBlock(nn.Module):
    """Causal gMLP block used for attn_type='mlp' layers."""

    dim: int
    dim_ff: int
    seq_len: int
    causal: bool = True
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True, decode: bool = False):
        x = nn.Dense(self.dim_ff, dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = nn.gelu(x)
        x = SpatialGatingUnit(
            seq_len=self.seq_len,
            causal=self.causal,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x, decode=decode)
        x = nn.Dense(self.dim, dtype=self.dtype, param_dtype=self.param_dtype)(x)
        return x


def shift_tokens_decode(
    x: jnp.ndarray,
    pos: jnp.ndarray,
    prev_token: jnp.ndarray,
    row_above_token: jnp.ndarray,
    text_len: int,
    image_size: int,
) -> jnp.ndarray:
    """Single-position token shift for the KV-cached decode loop.

    x: (b, n, d) current token features (n == 1 for the classic decode
    step); pos: scalar int32 global position, (b,) per-sequence positions
    (ragged decode offsets / continuous batching), or (b, n) per-token
    positions of a ragged BLOCK (the fused serving iteration) — every
    position test below is elementwise, so all forms broadcast;
    prev_token / row_above_token: (b, n, d) features of positions pos-1
    and pos-image_size (zeros when out of range / across a boundary).
    """
    if jnp.ndim(pos) == 1:
        pos = pos[:, None, None]  # broadcast per-sequence over (b, 1, d)
    elif jnp.ndim(pos) == 2:
        pos = pos[..., None]      # (b, n) per-token over (b, n, d)
    d = x.shape[-1]
    is_text = pos < text_len
    p_img = pos - text_len
    col = p_img % image_size
    row = p_img // image_size

    half, quarter = d // 2, d // 4

    # text branch: first half channels from previous token (zero at pos 0)
    prev_ok_text = (pos > 0) & is_text
    text_shift = jnp.where(prev_ok_text, prev_token[..., :half], 0.0)
    text_out = jnp.concatenate((text_shift, x[..., half:]), axis=-1)

    # image branch
    top_ok = row > 0
    left_ok = col > 0
    top = jnp.where(top_ok, row_above_token[..., :quarter], 0.0)
    left = jnp.where(left_ok, prev_token[..., quarter : 2 * quarter], 0.0)
    img_out = jnp.concatenate((top, left, x[..., 2 * quarter :]), axis=-1)

    return jnp.where(is_text, text_out, img_out)
