"""Decode KV-cache layout policy — one named decision point, observable.

The decode cache's array layout used to be an inline magic branch
(``flat = b == 8`` in ops/attention.py:_decode_caches): correct at the one
measured point, a silent perf cliff everywhere near it, and invisible to
users when it fell back. This module replaces it with a *policy*:

- ``"paged"``  — block-paged cache (ops/paged_kv.py): fixed 128-token pages
  in (b, n_pages, page, h*d) layout behind a per-sequence page table and a
  per-sequence (b,) write index. The per-step update touches one page row,
  so the update cost is a property of the CACHE, not of the batch size —
  the structural fix for the 4-D layout's whole-buffer dynamic-update-slice
  rewrites that made serving throughput non-monotone in batch (batch 32
  measured 6,050 tok/s vs batch 8's 6,832 on v5e, BENCH_r05). Also the only
  format with ragged per-sequence decode offsets (continuous batching).
- ``"flat"``  — (b, L, h*d): the measured batch-8 winner (+38% tok/s over
  4-D there, v5e 2026-07), and a measured LOSER at batches 1/4/16/32 on the
  same chip/compiler.
- ``"4d"``    — (b, L, h, d): the measured batch-1 winner (0.660 vs
  0.747 ms/token int8); its one-row update compiles to a positions-minor
  layout whose DUS tax grows with batch (trace-measured 43% of the batch-8
  decode program before the flat fix).

Default policy (the measured numbers above are the provenance): 4-D at
batch 1, flat at batch 8, paged everywhere else. Batch 1 and 8 keep their
proven layouts; every other batch — where 4-D was only ever the lesser
evil — gets the format whose update cost does not scale with the buffer.
Re-measure with ``bench.py --sweep`` on compiler/chip changes.

Every choice is emitted once per (format, batch) through the
``dalle_tpu.kv_policy`` logger and recorded in ``CHOICE_LOG`` so an
unexpected layout fallback is observable (bench.py surfaces the format in
its throughput records) instead of a silent perf cliff.

Overrides, strongest first:
- ``format_override(fmt)`` context manager (how an explicit
  ``cache_format=`` argument reaches the attention layers at trace time);
- ``DALLE_TPU_KV_FORMAT`` = paged|flat|4d;
- legacy ``DALLE_TPU_FLAT_KV`` = 0|1 (maps to 4d|flat), kept for
  re-measurement scripts.

Environment overrides are read at TRACE time: flipping one under an
already-cached jit requires ``jax.clear_caches()`` (the existing
re-measurement workflow; tests do the same).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
from typing import Iterator, Optional

logger = logging.getLogger("dalle_tpu.kv_policy")

FORMATS = ("paged", "flat", "4d")

DEFAULT_PAGE_SIZE = 128

# ------------------------------------------------------------ KV quant
#
# Storage quantization of the PAGED pools (ops/paged_kv.py): "int8"
# stores K/V pages as int8 with per-(token, head) symmetric scales in a
# parallel paged scale pool, quantized at append time and dequantized at
# READ time in-kernel — in the Pallas ragged path the int8 pages stream
# through VMEM and widen in registers (ops/ragged_attention.py), in the
# jnp reference path the gathered view dequantizes through the same
# formula (paged_kv.dequant), so the two paths cannot drift. The knob is
# orthogonal to the layout FORMAT above and applies to the paged format
# only (the flat/4d decode caches never consulted it — their one
# measured int8 experiment LOST on single-stream latency; see the
# measured note at the bottom of ops/attention.py. The serving engine's
# batched paged pools are a different regime: the largest HBM tenant
# under a stream-bound roofline, where halved bytes mean ~2x slots and
# ~2x prefix-cache arena at fixed HBM).
#
# Override channels, strongest first (mirroring the format channels; an
# invalid value fails TYPED at resolution time in every one of them):
# - ``quant_override(q)`` context manager (how an explicit ``kv_quant=``
#   argument — models/sampling.py:init_decode_cache, EngineConfig —
#   reaches the attention layers at trace time);
# - ``DALLE_TPU_KV_QUANT`` = none|int8;
# - default policy: "none".
#
# Parity tiers (docs/DESIGN.md §6.1): quantized-vs-quantized holds the
# standing BITWISE contract everywhere (cold vs warm prefix hit, split
# vs fused engines, preempt replay, spec decode) — quantization is a
# deterministic per-row elementwise map, so the PR 9/10/11 parity
# arguments carry over unchanged. Quantized-vs-f32 is a pinned
# token-AGREEMENT threshold (below), asserted in tests and reported by
# bench.py --serve; it is never a bitwise claim.

QUANTS = ("none", "int8")

# pinned quantized-vs-f32 token-agreement floor (fraction of generated
# positions whose sampled token matches the unquantized run, same seed):
# asserted by tests/test_kv_quant.py and tools/serve_smoke.py, reported
# by bench.py --serve. Position-wise agreement is chance-level after a
# first divergence, so the floor is deliberately below the typically
# observed ~1.0 on the tiny f32 CPU tier — it guards against the
# quantizer breaking (agreement collapsing toward the random-token
# floor), not against single near-tie sample flips.
KV_QUANT_TOKEN_AGREEMENT_MIN = 0.5


class InvalidKVFormatError(ValueError):
    """Raised at POLICY-RESOLUTION time for an unknown cache format (from
    ``DALLE_TPU_KV_FORMAT``, legacy ``DALLE_TPU_FLAT_KV``, or an explicit
    ``cache_format=`` argument) — a bad override must fail here, naming the
    valid formats, not as a shape error deep inside cache init. Subclasses
    ValueError so pre-existing ``except ValueError`` callers keep working."""

    def __init__(self, source: str, got: object, valid: tuple = FORMATS):
        super().__init__(
            f"{source} must be one of {valid}, got {got!r}"
        )
        self.source = source
        self.got = got
        self.valid = valid

# every (format, batch, reason) decision made this process, in order — the
# observable record bench.py attaches to its throughput entries
CHOICE_LOG: list = []
_EMITTED: set = set()

# a ContextVar, not a module global: concurrent traces (a serving layer
# jitting two generations with different formats on different threads)
# must not see each other's override
_OVERRIDE: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dalle_tpu_kv_format_override", default=None
)

_QUANT_OVERRIDE: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dalle_tpu_kv_quant_override", default=None
)


def tpu_auto_env(name: str) -> bool:
    """Tri-state env gate for TPU-only optimizations: "auto" (the
    default when the variable is unset) resolves to backend == "tpu";
    "1"/"0" force either way. ONE parser for every such knob —
    ``DALLE_TPU_LANE_PACK`` (ops/attention.py:lane_pack_enabled) and
    ``DALLE_TPU_RAGGED_KERNEL`` (ops/ragged_attention.py:use_kernel) —
    so platform resolution and error wording cannot drift between them.
    jax is imported lazily: only the "auto" branch needs a backend, and
    this module stays import-light for pure policy callers."""
    v = os.environ.get(name, "auto")
    if v not in ("auto", "0", "1"):
        raise ValueError(f"{name} must be 'auto', '0' or '1', got {v!r}")
    if v == "auto":
        import jax

        return jax.devices()[0].platform == "tpu"
    return v == "1"


def page_size() -> int:
    """Page row count; ``DALLE_TPU_KV_PAGE_SIZE`` overrides (tests use tiny
    pages to exercise page-boundary arithmetic on small models)."""
    raw = os.environ.get("DALLE_TPU_KV_PAGE_SIZE")
    if raw in (None, ""):
        return DEFAULT_PAGE_SIZE
    size = int(raw)
    if size <= 0:
        raise ValueError(f"DALLE_TPU_KV_PAGE_SIZE must be > 0, got {raw!r}")
    return size


@contextlib.contextmanager
def format_override(fmt: Optional[str]) -> Iterator[None]:
    """Pin the cache format for every ``choose_cache_format`` call in the
    block — the trace-time channel for an explicit ``cache_format=``
    argument (models/sampling.py wraps its whole traced body in this, so
    the format participates in the jit cache key as a static argument
    rather than as hidden module state)."""
    if fmt is not None and fmt not in FORMATS:
        raise InvalidKVFormatError("cache_format", fmt)
    token = _OVERRIDE.set(fmt)
    try:
        yield
    finally:
        _OVERRIDE.reset(token)


def _emit(fmt: str, batch: int, reason: str) -> None:
    key = (fmt, batch, reason)
    CHOICE_LOG.append({"cache_format": fmt, "batch": batch, "reason": reason})
    if key in _EMITTED:
        return
    _EMITTED.add(key)
    logger.info("decode KV cache format: %s (batch=%d, %s)", fmt, batch, reason)


def choose_cache_format(batch: int) -> str:
    """Resolve the decode cache format for a batch (called at trace time by
    ops/attention.py when no cache exists yet). See module docstring for the
    policy and its measured provenance."""
    override = _OVERRIDE.get()
    if override is not None:
        fmt, reason = override, "explicit override"
    else:
        env = os.environ.get("DALLE_TPU_KV_FORMAT")
        legacy = os.environ.get("DALLE_TPU_FLAT_KV")
        if env not in (None, ""):
            if env not in FORMATS:
                raise InvalidKVFormatError("DALLE_TPU_KV_FORMAT", env)
            fmt, reason = env, "DALLE_TPU_KV_FORMAT"
        elif legacy not in (None, ""):
            if legacy not in ("0", "1"):
                raise InvalidKVFormatError(
                    "DALLE_TPU_FLAT_KV", legacy, valid=("0", "1")
                )
            fmt, reason = ("flat" if legacy == "1" else "4d"), "DALLE_TPU_FLAT_KV"
        elif batch == 1:
            fmt, reason = "4d", "policy: measured batch-1 layout (v5e 2026-07)"
        elif batch == 8:
            fmt, reason = "flat", "policy: measured batch-8 layout (v5e 2026-07)"
        else:
            fmt, reason = "paged", "policy: batch-invariant page-local updates"
    _emit(fmt, batch, reason)
    return fmt


def resolve_format(cache_format: Optional[str], batch: int) -> str:
    """An explicit ``cache_format`` argument wins; ``None`` defers to the
    policy. Entry point for models/sampling.py."""
    if cache_format is not None:
        if cache_format not in FORMATS:
            raise InvalidKVFormatError("cache_format", cache_format)
        _emit(cache_format, batch, "cache_format argument")
        return cache_format
    return choose_cache_format(batch)


# ------------------------------------------------------------ KV quant


@contextlib.contextmanager
def quant_override(quant: Optional[str]) -> Iterator[None]:
    """Pin the KV storage quantization for every ``choose_kv_quant`` call
    in the block — the trace-time channel for an explicit ``kv_quant=``
    argument (models/sampling.py:init_decode_cache wraps its traced body
    in this, so the serving engine's caches can never drift from the
    ambient environment between the batched cache and its prefill
    template)."""
    if quant is not None and quant not in QUANTS:
        raise InvalidKVFormatError("kv_quant", quant, valid=QUANTS)
    token = _QUANT_OVERRIDE.set(quant)
    try:
        yield
    finally:
        _QUANT_OVERRIDE.reset(token)


def choose_kv_quant() -> str:
    """Resolve the paged-pool storage quantization ("none" | "int8") —
    called at trace time by ops/attention.py when no cache exists yet (a
    SUPPLIED cache's variables win there, exactly like the layout
    format). Channel order and error typing mirror
    ``choose_cache_format``; see the KV-quant block in the module
    docstring area above for the policy rationale."""
    override = _QUANT_OVERRIDE.get()
    if override is not None:
        quant, reason = override, "explicit override"
    else:
        env = os.environ.get("DALLE_TPU_KV_QUANT")
        if env not in (None, ""):
            if env not in QUANTS:
                raise InvalidKVFormatError(
                    "DALLE_TPU_KV_QUANT", env, valid=QUANTS
                )
            quant, reason = env, "DALLE_TPU_KV_QUANT"
        else:
            quant, reason = "none", "policy: default unquantized"
    key = ("kv_quant", quant, reason)
    if key not in _EMITTED:
        _EMITTED.add(key)
        logger.info("decode KV quantization: %s (%s)", quant, reason)
    return quant


def resolve_quant(kv_quant: Optional[str]) -> str:
    """An explicit ``kv_quant`` argument wins; ``None`` defers to the
    override/env/policy chain. Entry point for
    models/sampling.py:init_decode_cache and the serving EngineConfig —
    an invalid value fails TYPED here, at resolution time, naming the
    valid quants (never as a dtype error deep inside cache init)."""
    if kv_quant is not None:
        if kv_quant not in QUANTS:
            raise InvalidKVFormatError("kv_quant", kv_quant, valid=QUANTS)
        return kv_quant
    return choose_kv_quant()
