"""Version-compat shims for JAX APIs that moved/renamed across releases.

The tree targets current JAX (`jax.shard_map`, `pltpu.CompilerParams`);
older releases (<= 0.4.x, like some CI/container images) ship the same
functionality as `jax.experimental.shard_map.shard_map(check_rep=...)` and
`pltpu.TPUCompilerParams`. Every call site routes through here so the
whole suite runs on either — the new API is the canonical spelling, the
old one is adapted.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
    """``jax.shard_map`` with the old experimental fallback: ``check_vma``
    was ``check_rep`` there, and explicit ``axis_names`` were expressed as
    the complementary ``auto`` set."""
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as _sm

    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, **kw,
    )


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (renamed from ``TPUCompilerParams``)."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
