"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence parallelism — its long-sequence levers are
sparse attention patterns and reversible layers (SURVEY.md §5.7); sequence
length is fixed at text + image_fmap**2 (dalle_pytorch.py:352). On TPU,
sequence parallelism is a first-class scaling axis: activations are sharded
over the ``sp`` mesh axis so per-chip activation memory and attention FLOPs
shrink by the sp extent, with the K/V exchange riding ICI.

Two complementary schemes, both written as *per-shard* bodies to be run under
``jax.shard_map`` (the surrounding network stays GSPMD/pjit-sharded — only
attention, whose mixing is global over the sequence, needs manual
collectives):

- ``ring_attention``: flash-style online-softmax accumulation while K/V
  chunks rotate around the ring via ``jax.lax.ppermute``. Used for dense
  causal ("full") layers. Causality is exploited per source chunk: blocks
  strictly in the future contribute nothing and their matmuls are skipped
  with ``lax.cond``, so the expected FLOP cost matches causal attention.
  Each hop's ppermute overlaps with the current chunk's compute (XLA
  schedules the collective-permute asynchronously on ICI).

- ``ulysses_attend``: two ``jax.lax.all_to_all`` calls re-shard
  (batch, heads/sp, FULL seq) <-> (batch, heads, seq/sp), running an
  arbitrary *local* attention pattern (axial / conv-like / block-sparse /
  non-causal CLIP) in between. This keeps every static pattern mask exactly
  as defined over the full sequence — no per-pattern communication logic.

Numerics match ``ops.attention.dense_attend``: logits and softmax
accumulate in float32 regardless of input dtype; fully-masked query rows
produce exactly 0 (the reference never hits this case; see
ADVICE round-1 on the flash kernel's contract).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
    sm_scale: float = 1.0,
    key_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Per-shard ring attention body (run under ``shard_map``).

    q, k, v: (b, h, n_local, d) — this shard's contiguous chunk of the
    sequence (shard i holds global rows [i*n_local, (i+1)*n_local)).
    ``key_mask``: optional (b, n_local) bool chunk of a global key-padding
    mask (True = attend); it rotates around the ring with its k/v chunk.
    Returns the local (b, h, n_local, d) output chunk.
    """
    b, h, nl, d = q.shape
    my = jax.lax.axis_index(axis_name)

    m = jnp.full((b, h, nl, 1), NEG_INF, jnp.float32)  # running row max
    l = jnp.zeros((b, h, nl, 1), jnp.float32)  # running row sum
    acc = jnp.zeros((b, h, nl, d), jnp.float32)  # unnormalized output

    local_causal = jnp.tril(jnp.ones((nl, nl), bool))[None, None]
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def block_update(q, k, v, km, m, l, acc, mask):
        s = jnp.einsum(
            "bhid,bhjd->bhij", q, k, preferred_element_type=jnp.float32
        ) * sm_scale
        if km is not None:
            kmask = km[:, None, None, :]
            mask = kmask if mask is None else mask & kmask
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # exp(NEG_INF - NEG_INF) would be 1 for masked entries of a row whose
        # running max is still NEG_INF; force those to exactly 0
        p = jnp.where(s <= NEG_INF, 0.0, jnp.exp(s - m_new))
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "bhij,bhjd->bhid",
            p.astype(v.dtype),
            v,
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    # python-unrolled over the (small, static) ring extent; after step s the
    # local k/v buffer holds the chunk originating from shard (my - s) % size
    for s in range(axis_size):
        src = (my - s) % axis_size

        if causal:
            def visit(args):
                k, v, km, m, l, acc = args
                # src < my: fully visible. src == my: local causal triangle.
                mask = (src < my) | local_causal
                return block_update(q, k, v, km, m, l, acc, mask)

            def skip(args):
                k, v, km, m, l, acc = args
                return m, l, acc

            m, l, acc = jax.lax.cond(
                src <= my, visit, skip, (k, v, key_mask, m, l, acc)
            )
        else:
            m, l, acc = block_update(q, k, v, key_mask, m, l, acc, None)

        if s != axis_size - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            if key_mask is not None:
                key_mask = jax.lax.ppermute(key_mask, axis_name, perm)

    out = acc / jnp.maximum(l, 1.0e-30)
    out = jnp.where(l > 0.0, out, 0.0)
    return out.astype(q.dtype)


def ulysses_attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    attend_fn: Callable[..., jnp.ndarray],
    key_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Per-shard Ulysses (all-to-all) attention body (run under shard_map).

    q, k, v: (b, h_local, n_local, d). Re-shards to (b, h_local/sp, n, d) so
    ``attend_fn(q, k, v, key_mask)`` sees the FULL sequence with a head
    subset, then re-shards the output back to the sequence layout.
    ``attend_fn`` must be head-elementwise (true for every pattern path in
    ops/attention.py). ``key_mask``: optional (b, n_local) bool chunk,
    all-gathered to the full (b, n) mask for the local call.
    """
    h_local = q.shape[1]
    assert h_local % axis_size == 0, (
        f"local head count {h_local} not divisible by sp={axis_size}; "
        f"reduce sp or tp so heads/(tp*sp) is integral"
    )

    def to_heads(t):  # gather seq, scatter heads
        return jax.lax.all_to_all(
            t, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def to_seq(t):  # gather heads, scatter seq
        return jax.lax.all_to_all(
            t, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    km = None
    if key_mask is not None:
        km = jax.lax.all_gather(key_mask, axis_name, axis=1, tiled=True)
    out = attend_fn(to_heads(q), to_heads(k), to_heads(v), km)
    return to_seq(out)
