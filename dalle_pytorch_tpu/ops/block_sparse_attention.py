"""Block-sparse Pallas attention: the grid visits only live block pairs.

The flash kernel (ops/flash_attention.py) streams EVERY (q-block, k-block)
pair and uses its scalar-prefetch visit table to skip compute on dead
blocks — index maps stay affine, so dead blocks still pay their K/V DMA.
That is the right trade for near-dense patterns, and it is why BENCH_r05
measured every sparse/axial/conv variant at 0.97-0.99x of full attention:
the sparse patterns pay full memory traffic plus a streamed mask.

Here the grid itself is the sparsity pattern. A host-compiled
``BlockLayout`` flattens the live (q-block, k-block) pairs of the static
pattern (ops/masks.py) into scalar-prefetch tables, and the kernel grid is
``(b*h, n_live_pairs)`` — the K/V index maps dereference the table, so
each step DMAs a DISTINCT live block and Mosaic's double buffering
survives (the ragged decode kernel, ops/ragged_attention.py, established
this idiom: table-indexed page fetches pipeline fine; what measured 23x
slower in the flash experiment was CLAMPING dead steps to re-fetch the
same block). Dead blocks are simply never part of the grid: no DMA, no
compute, and the ``pl.CostEstimate`` scales with live pairs, so the
scheduler sees the real FLOP saving.

Pairs are ordered q-block-major with first/last flags riding the table;
online softmax accumulates (m, l, acc) in VMEM scratch across a q-block's
visited pairs and finalizes on the last one. Partial blocks (diagonal
causal crossings, pattern edges) stream their slice of the elementwise
mask; the backward is the FlashAttention-2 decomposition over the same
pair list (dq q-major, dk/dv over a k-major reordering).

The jnp reference path shares ``cache_block_attend``'s einsums with the
expanded elementwise mask (the ops/ragged_attention.py idiom), so kernel
vs reference parity is pinned allclose in interpret mode on CPU while the
dense-mask semantics stay the single source of truth.

The SP half: ``compile_sp_plan`` assigns q-blocks to ``sp``-axis chips
with a DUAL-BALANCED objective (db-SP, PAPERS.md 2511.23113): greedy LPT
on per-block visited-pair counts under a per-chip block-count cap, so both
the q-block count and the visited-pair count are even per chip — an axial
pattern's skewed rows (text rows attend everything, image rows a thin
band) no longer serialize the slowest chip. ``sp_block_sparse_attend`` is
the shard_map body: all-gather K/V/Q over sp, each chip computes its
assigned (permuted) q-rows, and a static inverse permutation restores
natural order before each chip returns its contiguous shard.

Policy: ``DALLE_TPU_SPARSE_KERNEL`` (unset/"auto" = TPU only, "0"/"1"
force — kv_policy.tpu_auto_env semantics); the dense-mask paths remain the
fallback and the off-TPU default.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .jax_compat import tpu_compiler_params

NEG_INF = -1e30
LANES = 128

# production block edge: the lane dimension must be a multiple of 128 and
# per-grid-step overhead dominates below it (the flash kernel's measured
# floor); layouts for tests/CPU may use any block sizes in interpret mode
DEFAULT_BLOCK = 128

# routing threshold: the pair grid engages only when the compiled layout
# skips at least this much of the dense-causal pair set. A layout whose
# live stride is finer than the block edge (axial_col at fmap <= 128, the
# 16-block DeepSpeed-style random layout) visits every pair — frac 1.0 —
# and would pay pair-grid overhead for zero skipped FLOPs; those patterns
# stay on the dense/flash paths until their geometry actually block-skips
ENGAGE_FRAC = 0.9


def sparse_kernel_enabled() -> bool:
    """Policy knob for routing sparse patterns through this kernel.
    "auto"/unset: TPU only (the CPU tier keeps the dense-mask paths that
    every bitwise contract is pinned on); ``DALLE_TPU_SPARSE_KERNEL=0|1``
    forces either way (tests/bench use 1 with interpret mode on CPU)."""
    from .kv_policy import tpu_auto_env

    return tpu_auto_env("DALLE_TPU_SPARSE_KERNEL")


# ------------------------------------------------------------------- layout


def _pair_lists(visit: np.ndarray):
    """q-major live pair arrays from a (nq, nk) visit map, with synthetic
    all-masked pairs for empty q rows so every output block is written
    (an empty row finalizes with l == 0 -> exact 0 output)."""
    nq, nk = visit.shape
    q_idx, k_idx, kclass = [], [], []
    for qb in range(nq):
        cols = np.flatnonzero(visit[qb])
        if cols.size == 0:
            # synthetic pair: class 0 tells the kernel the mask block may
            # contain live bits belonging to OTHER rows — mask everything
            q_idx.append(qb)
            k_idx.append(min(qb, nk - 1))
            kclass.append(0)
            continue
        for kb in cols:
            q_idx.append(qb)
            k_idx.append(kb)
            kclass.append(int(visit[qb, kb]))
    q_idx = np.asarray(q_idx, np.int32)
    k_idx = np.asarray(k_idx, np.int32)
    kclass = np.asarray(kclass, np.int32)
    first = np.concatenate(([1], (q_idx[1:] != q_idx[:-1]).astype(np.int32)))
    last = np.concatenate(((q_idx[1:] != q_idx[:-1]).astype(np.int32), [1]))
    return q_idx, k_idx, kclass, first, last


def _table(q_idx, k_idx, kclass, first, last) -> np.ndarray:
    """(5, P) int32 scalar-prefetch payload: rows are q-block index,
    k-block index, visit class, first-of-group, last-of-group."""
    return np.stack([q_idx, k_idx, kclass, first, last]).astype(np.int32)


@dataclasses.dataclass(frozen=True, eq=False)
class BlockLayout:
    """Host-compiled block program for one static pattern.

    Hash/eq by identity (the StaticMask idiom, ops/flash_attention.py):
    build once per (pattern config, n) via a cached constructor so jit and
    custom_vjp see a stable static argument. ``mask`` is the elementwise
    (n_pad, n_pad) may-attend matrix, zero-padded past ``n`` — the single
    source of truth both the kernel (streamed int8 blocks) and the jnp
    reference consume, so they cannot drift.
    """

    n: int
    n_pad: int
    block_q: int
    block_k: int
    visit: np.ndarray  # (nq, nk) int32: 0 skip / 1 partial / 2 dense
    mask: np.ndarray  # (n_pad, n_pad) bool
    fwd_table: np.ndarray  # (5, Pq) int32, q-major
    kv_table: np.ndarray  # (5, Pk) int32, k-major

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    @property
    def nq(self) -> int:
        return self.visit.shape[0]

    @property
    def nk(self) -> int:
        return self.visit.shape[1]

    @property
    def n_pairs(self) -> int:
        return int((self.visit > 0).sum())

    @property
    def dense_pairs(self) -> int:
        """Block pairs a full-causal layout visits at these block sizes —
        the denominator of the block-skip win."""
        q_hi = (np.arange(self.nq) + 1) * self.block_q - 1
        k_lo = np.arange(self.nk) * self.block_k
        return int((k_lo[None, :] <= q_hi[:, None]).sum())

    @property
    def visited_block_frac(self) -> float:
        """Live pairs / dense-causal pairs: the block-skip FLOP ratio the
        bench asserts < 1.0 for every sparse layout."""
        return self.n_pairs / max(self.dense_pairs, 1)


def compile_block_layout(
    mask: np.ndarray,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
) -> BlockLayout:
    """Compile an elementwise (n, n) may-attend mask into a BlockLayout.

    Ragged tails are zero-padded to the block grid: padded keys are never
    attendable, padded query rows are fully masked and finalize to exact 0
    (sliced off by the caller)."""
    mask = np.asarray(mask, dtype=bool)
    n = mask.shape[0]
    assert mask.shape == (n, n), mask.shape
    nq = -(-n // block_q)
    nk = -(-n // block_k)
    n_pad_q, n_pad_k = nq * block_q, nk * block_k
    n_pad = max(n_pad_q, n_pad_k)
    padded = np.zeros((n_pad, n_pad), dtype=bool)
    padded[:n, :n] = mask

    visit = np.zeros((nq, nk), dtype=np.int32)
    for qb in range(nq):
        row = padded[qb * block_q : (qb + 1) * block_q]
        for kb in range(nk):
            blk = row[:, kb * block_k : (kb + 1) * block_k]
            visit[qb, kb] = 0 if not blk.any() else (2 if blk.all() else 1)

    fwd = _table(*_pair_lists(visit))
    # k-major reordering for the dkv backward: transpose the visit map,
    # build groups per k block, swap the index rows back to (q, k) order
    tk = _table(*_pair_lists(np.ascontiguousarray(visit.T)))
    kv = np.stack([tk[1], tk[0], tk[2], tk[3], tk[4]]).astype(np.int32)
    return BlockLayout(
        n=n, n_pad=n_pad, block_q=block_q, block_k=block_k,
        visit=visit, mask=padded, fwd_table=fwd, kv_table=kv,
    )


# ------------------------------------------------------------------ kernels


def _masked_exp(s, x):
    """exp(s - x) with fully-masked entries forced to 0 (the flash kernel's
    guard): rows dead in every visited block keep m/lse at NEG_INF, where
    exp(s - x) would be 1."""
    return jnp.where(s > 0.5 * NEG_INF, jnp.exp(s - x), 0.0)


def _row_vec(ref):
    """(1, 1, bq) ref block -> (bq, 1) f32."""
    return jax.lax.transpose(ref[0], (1, 0))


def _pair_scores(q, k, sm_scale, mask_ref, kmask_ref, kclass):
    """(bq, bk) f32 scores for one live pair. The streamed mask block is
    applied unless the pair is classified dense (class 2: every bit set,
    the where would be a no-op — skipping it keeps dense blocks pure MXU
    work, the 'causal masking only on diagonal/partial blocks' rule).
    Synthetic class-0 pairs (empty q rows) mask everything."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    # i8 -> i32 widen before compare: Mosaic on v5e cannot lower cmpi on
    # the packed vector<..xi8> layout (flash kernel note)
    live = mask_ref[:].astype(jnp.int32) > 0
    s = jnp.where(kclass == 2, s, jnp.where(live, s, NEG_INF))
    s = jnp.where(kclass == 0, NEG_INF, s)
    if kmask_ref is not None:
        s = jnp.where(kmask_ref[0] > 0, s, NEG_INF)  # (1, bk) over rows
    return s


def _fwd_kernel(
    tab_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, o_ref, lse_ref,
    m_scr, l_scr, acc_scr,
    *, sm_scale,
):
    p = pl.program_id(1)
    kclass = tab_ref[2, p]

    @pl.when(tab_ref[3, p] == 1)  # first pair of this q block
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    s = _pair_scores(q_ref[0], k_ref[0], sm_scale, mask_ref, kmask_ref, kclass)
    m_prev = m_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    pv = _masked_exp(s, m_new)
    corr = jnp.exp(m_prev - m_new)
    l_scr[:, 0:1] = l_scr[:, 0:1] * corr + jnp.sum(pv, axis=-1, keepdims=True)
    m_scr[:, 0:1] = m_new
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        pv.astype(v_ref.dtype), v_ref[0],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(tab_ref[4, p] == 1)  # last pair: finalize this q block
    def _():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)
        lse_ref[0] = jax.lax.transpose(lse, (1, 0))


def _bwd_dq_kernel(
    tab_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, do_ref, lse_ref,
    delta_ref, dq_ref, dq_scr,
    *, sm_scale,
):
    p = pl.program_id(1)

    @pl.when(tab_ref[3, p] == 1)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = _pair_scores(q, k, sm_scale, mask_ref, kmask_ref, tab_ref[2, p])
    pv = _masked_exp(s, _row_vec(lse_ref))
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = pv * (dp - _row_vec(delta_ref)) * sm_scale
    dq_scr[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(tab_ref[4, p] == 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    tab_ref, q_ref, k_ref, v_ref, mask_ref, kmask_ref, do_ref, lse_ref,
    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
    *, sm_scale,
):
    p = pl.program_id(1)

    @pl.when(tab_ref[3, p] == 1)  # first pair of this k block
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = _pair_scores(q, k, sm_scale, mask_ref, kmask_ref, tab_ref[2, p])
    pv = _masked_exp(s, _row_vec(lse_ref))
    dv_scr[:] += jax.lax.dot_general(
        pv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = (pv * (dp - _row_vec(delta_ref)) * sm_scale).astype(q.dtype)
    dk_scr[:] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(tab_ref[4, p] == 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ------------------------------------------------------------------ plumbing


def _pair_cost(n_pairs, n_qblocks, bh, bq, bk, d, dots, dtype_bytes):
    """Live-pair cost: unlike the flash kernel (affine maps, every block
    DMAs), both compute AND streamed K/V traffic scale with the live pair
    count — this estimate is the block-skip win the scheduler sees."""
    return pl.CostEstimate(
        flops=bh * n_pairs * dots * 2 * bq * bk * d,
        transcendentals=bh * n_pairs * bq * bk,
        bytes_accessed=bh
        * (n_pairs * 2 * bk + n_qblocks * 2 * bq)
        * d
        * dtype_bytes,
    )


def _pair_call(kernel, grid, in_specs, out_specs, out_shape, scratch, table,
               operands, interpret, cost):
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
        ),
        out_shape=out_shape,
        # batch*heads steps are independent; the pair dimension accumulates
        # (q-block groups are contiguous runs) so it must stay ordered
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        cost_estimate=cost,
        interpret=interpret,
    )(table, *operands)


def _opt_kmask(kernel, has_km, n_out, n_scratch):
    """Adapt a kernel with a (mask_ref, kmask_ref) slot pair to calls
    without the optional runtime key-mask operand."""

    def wrapped(*refs):
        split = len(refs) - n_out - n_scratch
        ins = list(refs[:split])
        rest = refs[split:]
        fixed, tail = ins[:5], ins[5:]  # tab, q, k, v, mask | optional km
        km = tail.pop(0) if has_km else None
        return kernel(*fixed, km, *tail, *rest)

    return wrapped


def _bcast_key_mask(key_mask, bh, heads, n):
    """(b, n) bool -> (b*h, 1, n) int32 streamed operand (the flash
    kernel's layout: int32 because Mosaic v5e cannot compare packed i8
    on a (1, 1, bk) block)."""
    b = bh // heads
    assert key_mask.shape == (b, n), (key_mask.shape, (b, n))
    return jnp.broadcast_to(
        key_mask[:, None, :].astype(jnp.int32), (b, heads, n)
    ).reshape(bh, 1, n)


def _specs(bq, bk, d, has_km):
    """Common forward/backward input specs over the scalar pair table:
    K/V index maps dereference the table, so every grid step fetches a
    DISTINCT live block (pipelining-safe, the ragged-kernel idiom);
    the q/out maps revisit their block across a contiguous pair run."""

    def q_im(bhi, p, s):
        return (bhi, s[0, p], 0)

    def kv_im(bhi, p, s):
        return (bhi, s[1, p], 0)

    def mask_im(bhi, p, s):
        return (s[0, p], s[1, p])

    base = [
        pl.BlockSpec((1, bq, d), q_im),
        pl.BlockSpec((1, bk, d), kv_im),
        pl.BlockSpec((1, bk, d), kv_im),
        pl.BlockSpec((bq, bk), mask_im),
    ]
    if has_km:
        base.append(pl.BlockSpec((1, 1, bk), lambda bhi, p, s: (bhi, 0, s[1, p])))
    return base, q_im, kv_im


def _bs_fwd(q, k, v, key_mask, mask_i8, fwd_table, kv_table, sm_scale,
            block_q, block_k, interpret):
    """Forward over flattened (b*h, n, d) operands; returns (o, lse)."""
    bh, nq_rows, d = q.shape
    nk_rows = k.shape[1]
    bq, bk = block_q, block_k
    nq = nq_rows // bq
    n_pairs = fwd_table.shape[1]

    in_specs, q_im, _ = _specs(bq, bk, d, key_mask is not None)
    operands = [q, k, v, mask_i8]
    if key_mask is not None:
        operands.append(key_mask)

    kernel = _opt_kmask(
        functools.partial(_fwd_kernel, sm_scale=sm_scale),
        key_mask is not None, n_out=2, n_scratch=3,
    )
    o, lse = _pair_call(
        kernel,
        grid=(bh, n_pairs),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), q_im),
            pl.BlockSpec((1, 1, bq), lambda bhi, p, s: (bhi, 0, s[0, p])),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, nq_rows, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, nq_rows), jnp.float32),
        ],
        scratch=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        table=fwd_table,
        operands=operands,
        interpret=interpret,
        cost=_pair_cost(n_pairs, nq, bh, bq, bk, d, 2, q.dtype.itemsize),
    )
    del nk_rows
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _pair_attention(q, k, v, key_mask, mask_i8, fwd_table, kv_table,
                    sm_scale, block_q, block_k, interpret):
    """custom_vjp core over flattened operands. The tables and mask are
    TRACED operands (int gradients are float0 zeros, the flash key-mask
    idiom) so the sp path can select a chip's tables with axis_index —
    only block sizes and the pair counts (via the table shapes) are
    static."""
    o, _ = _bs_fwd(q, k, v, key_mask, mask_i8, fwd_table, kv_table,
                   sm_scale, block_q, block_k, interpret)
    return o


def _pair_fwd_rule(q, k, v, key_mask, mask_i8, fwd_table, kv_table,
                   sm_scale, block_q, block_k, interpret):
    o, lse = _bs_fwd(q, k, v, key_mask, mask_i8, fwd_table, kv_table,
                     sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, key_mask, mask_i8, fwd_table, kv_table, o, lse)


def _pair_bwd_rule(sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, key_mask, mask_i8, fwd_table, kv_table, o, lse = res
    bh, nq_rows, d = q.shape
    nk_rows = k.shape[1]
    bq, bk = block_q, block_k
    nq, nk = nq_rows // bq, nk_rows // bk
    n_pairs_q = fwd_table.shape[1]
    n_pairs_k = kv_table.shape[1]

    # delta = rowsum(do * o): one fused elementwise pass (the split flash
    # kernels derive it in-kernel; at a pair grid the q block is revisited
    # per pair, so hoisting it out is both simpler and cheaper)
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).reshape(bh, 1, nq_rows)
    lsef = lse.reshape(bh, 1, nq_rows)

    has_km = key_mask is not None
    in_specs, q_im, kv_im = _specs(bq, bk, d, has_km)
    km_op = [key_mask] if has_km else []

    def row_im(bhi, p, s):
        return (bhi, 0, s[0, p])

    # ---- dq over the q-major pair list ------------------------------------
    dq_specs = in_specs + [
        pl.BlockSpec((1, bq, d), q_im),
        pl.BlockSpec((1, 1, bq), row_im),
        pl.BlockSpec((1, 1, bq), row_im),
    ]
    dq_kernel = _opt_kmask(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale),
        has_km, n_out=1, n_scratch=1,
    )
    (dq,) = _pair_call(
        dq_kernel,
        grid=(bh, n_pairs_q),
        in_specs=dq_specs,
        out_specs=[pl.BlockSpec((1, bq, d), q_im)],
        out_shape=[jax.ShapeDtypeStruct((bh, nq_rows, d), q.dtype)],
        scratch=[pltpu.VMEM((bq, d), jnp.float32)],
        table=fwd_table,
        operands=[q, k, v, mask_i8, *km_op, do, lsef, delta],
        interpret=interpret,
        cost=_pair_cost(n_pairs_q, nq, bh, bq, bk, d, 3, q.dtype.itemsize),
    )

    # ---- dk/dv over the k-major pair list ---------------------------------
    dkv_specs = in_specs + [
        pl.BlockSpec((1, bq, d), q_im),
        pl.BlockSpec((1, 1, bq), row_im),
        pl.BlockSpec((1, 1, bq), row_im),
    ]
    dkv_kernel = _opt_kmask(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale),
        has_km, n_out=2, n_scratch=2,
    )
    dk, dv = _pair_call(
        dkv_kernel,
        grid=(bh, n_pairs_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), kv_im),
            pl.BlockSpec((1, bk, d), kv_im),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, nk_rows, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nk_rows, d), q.dtype),
        ],
        scratch=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        table=kv_table,
        operands=[q, k, v, mask_i8, *km_op, do, lsef, delta],
        interpret=interpret,
        cost=_pair_cost(n_pairs_k, nk, bh, bq, bk, d, 4, q.dtype.itemsize),
    )

    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    dkm = None if key_mask is None else f0(key_mask)
    return dq, dk, dv, dkm, f0(mask_i8), f0(fwd_table), f0(kv_table)


_pair_attention.defvjp(_pair_fwd_rule, _pair_bwd_rule)


# ------------------------------------------------------------------- public


def _pad_rows(t, rows, axis):
    pad = rows - t.shape[axis]
    if pad == 0:
        return t
    widths = [(0, 0)] * t.ndim
    widths[axis] = (0, pad)
    return jnp.pad(t, widths)


def block_sparse_attention(
    q, k, v, layout: BlockLayout,
    key_mask=None,
    sm_scale: Optional[float] = None,
    interpret: bool = False,
):
    """Block-sparse attention over (b, h, n, d); q NOT pre-scaled.

    ``layout``: a compiled BlockLayout for this n (build via
    compile_block_layout / attention._cached_block_layout). ``key_mask``:
    runtime (b, n) bool, True = attendable; rows with every key masked
    return exactly 0 (the flash contract — NOT the dense softmax's
    uniform average, which is why parity tests compare live rows)."""
    b, h, n, d = q.shape
    assert layout.n == n, (layout.n, n)
    scale = d**-0.5 if sm_scale is None else sm_scale
    bh = b * h
    qf, kf, vf = (
        _pad_rows(t.reshape(bh, n, d), layout.n_pad, 1) for t in (q, k, v)
    )
    kmf = None
    if key_mask is not None:
        kmf = _pad_rows(
            _bcast_key_mask(key_mask, bh, h, n), layout.n_pad, 2
        )
    o = _pair_attention(
        qf, kf, vf, kmf,
        jnp.asarray(layout.mask, jnp.int8),
        jnp.asarray(layout.fwd_table),
        jnp.asarray(layout.kv_table),
        scale, layout.block_q, layout.block_k, interpret,
    )
    return o[:, :n].reshape(b, h, n, d)


def reference_attend(
    q, k, v, layout: BlockLayout,
    key_mask=None,
    sm_scale: Optional[float] = None,
    stable: bool = False,
):
    """jnp parity path over (b, h, n, d): the layout's elementwise mask fed
    through ``cache_block_attend``'s einsums (the ops/ragged_attention.py
    idiom) — exact dense-mask semantics by construction, and the CPU
    tier-1 oracle the kernel is pinned against."""
    from .attention import cache_block_attend

    b, h, n, d = q.shape
    scale = d**-0.5 if sm_scale is None else sm_scale
    allowed = jnp.asarray(layout.mask[:n, :n])[None, None]  # (1, 1, n, n)
    if key_mask is not None:
        allowed = allowed & key_mask[:, None, None, :]
    out = cache_block_attend(
        (q * scale).transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        allowed,
        stable,
    )
    return out.transpose(0, 2, 1, 3)


# =============================================================== SP balancing


def dual_balanced_assignment(
    weights: np.ndarray, n_chips: int, cap: Optional[int] = None
) -> np.ndarray:
    """db-SP dual-balanced q-block -> chip map (PAPERS.md 2511.23113).

    Greedy LPT on per-block visited-pair counts under a per-chip
    block-count cap ceil(nq / n_chips): both objectives are balanced at
    once — block counts within one of each other (the cap), and pair
    loads within one block's weight (the LPT bound), so an axial
    pattern's heavy text rows spread across chips instead of serializing
    the ring. Host-side numpy over the static layout; nothing traced."""
    weights = np.asarray(weights, dtype=np.int64)
    nq = weights.shape[0]
    assert n_chips >= 1
    if cap is None:
        cap = -(-nq // n_chips)
    loads = np.zeros(n_chips, dtype=np.int64)
    counts = np.zeros(n_chips, dtype=np.int64)
    assign = np.zeros(nq, dtype=np.int64)
    for blk in np.argsort(-weights, kind="stable"):
        elig = np.flatnonzero(counts < cap)
        chip = elig[np.argmin(loads[elig], axis=0)]
        assign[blk] = chip
        loads[chip] += weights[blk]
        counts[chip] += 1
    return assign


@dataclasses.dataclass(frozen=True, eq=False)
class SpPlan:
    """Host-compiled per-chip execution plan for sequence-parallel
    block-sparse attention (identity hash, like BlockLayout). All arrays
    are chip-major so a shard_map body selects its slice with
    ``axis_index`` — the plan itself stays static data."""

    layout: BlockLayout
    sp: int
    assign: np.ndarray  # (nq,) chip per q block
    row_table: np.ndarray  # (sp, rows_pc) int32 global q-row per local row
    inv_perm: np.ndarray  # (n_pad,) int32: natural row -> gathered position
    masks: np.ndarray  # (sp, rows_pc, n_pad) bool: per-chip mask rows
    fwd_tables: np.ndarray  # (sp, 5, Pq_max) int32, local q indices
    kv_tables: np.ndarray  # (sp, 5, Pk_max) int32, local q indices
    pair_counts: np.ndarray  # (sp,) live pairs per chip (balance metric)

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    @property
    def rows_per_chip(self) -> int:
        return self.row_table.shape[1]


def _chip_tables(visit_rows: np.ndarray, kind: str) -> np.ndarray:
    if kind == "fwd":
        return _table(*_pair_lists(visit_rows))
    tk = _table(*_pair_lists(np.ascontiguousarray(visit_rows.T)))
    return np.stack([tk[1], tk[0], tk[2], tk[3], tk[4]]).astype(np.int32)


def _pad_table(tab: np.ndarray, width: int, kind: str) -> np.ndarray:
    """Right-pad a (5, P) pair table to a common static width with no-op
    pairs: class 0 (mask-everything), first=0 so scratch is not reset,
    last=0 so nothing finalizes — trailing pads leave the already-written
    output blocks untouched."""
    pad = width - tab.shape[1]
    if pad == 0:
        return tab
    q_end, k_end = tab[0, -1], tab[1, -1]
    filler = np.stack([
        np.full(pad, q_end), np.full(pad, k_end),
        np.zeros(pad), np.zeros(pad), np.zeros(pad),
    ]).astype(np.int32)
    return np.concatenate([tab, filler], axis=1)


def compile_sp_plan(layout: BlockLayout, sp: int) -> SpPlan:
    """Compile the dual-balanced per-chip plan from a BlockLayout."""
    nq, bq = layout.nq, layout.block_q
    weights = (layout.visit > 0).sum(axis=1)
    assign = dual_balanced_assignment(weights, sp)
    cap = -(-nq // sp)

    row_table = np.zeros((sp, cap * bq), dtype=np.int32)
    inv_perm = np.zeros(layout.n_pad, dtype=np.int32)
    masks = np.zeros((sp, cap * bq, layout.n_pad), dtype=bool)
    fwd_tabs, kv_tabs, pair_counts = [], [], []
    for chip in range(sp):
        blocks = np.flatnonzero(assign == chip)
        rows = np.concatenate(
            [np.arange(b * bq, (b + 1) * bq) for b in blocks]
        ) if blocks.size else np.zeros(0, np.int64)
        # pad empty slots with row 0: computed then dropped (inv_perm
        # never points at a pad slot)
        padded = np.concatenate([rows, np.zeros(cap * bq - rows.size, np.int64)])
        row_table[chip] = padded
        inv_perm[rows] = chip * cap * bq + np.arange(rows.size)
        masks[chip] = layout.mask[padded] if padded.size else masks[chip]
        masks[chip, rows.size:] = False  # pad rows attend nothing
        # local visit map: assigned block rows first, all-skip pad rows after
        visit_rows = np.zeros((cap, layout.nk), dtype=np.int32)
        visit_rows[: blocks.size] = layout.visit[blocks]
        fwd_tabs.append(_chip_tables(visit_rows, "fwd"))
        kv_tabs.append(_chip_tables(visit_rows, "kv"))
        pair_counts.append(int((visit_rows > 0).sum()))

    wq = max(t.shape[1] for t in fwd_tabs)
    wk = max(t.shape[1] for t in kv_tabs)
    return SpPlan(
        layout=layout, sp=sp, assign=assign,
        row_table=row_table, inv_perm=inv_perm, masks=masks,
        fwd_tables=np.stack([_pad_table(t, wq, "fwd") for t in fwd_tabs]),
        kv_tables=np.stack([_pad_table(t, wk, "kv") for t in kv_tabs]),
        pair_counts=np.asarray(pair_counts, np.int64),
    )


def sp_block_sparse_attend(
    q, k, v, plan: SpPlan, axis_name: str, axis_size: int,
    *, sm_scale: Optional[float] = None, key_mask=None,
    use_kernel: bool = False, interpret: bool = False, stable: bool = False,
):
    """shard_map body: dual-balanced sequence-parallel sparse attention.

    q, k, v: LOCAL (b, h, n/sp, d) shards of the natural sequence order.
    K/V (and Q, which is re-dealt to chips by the balanced assignment) are
    all-gathered over ``axis_name``; each chip computes its assigned
    q-rows — via the pair kernel when ``use_kernel`` (chip tables selected
    with axis_index as traced operands) or the dense-mask jnp path
    otherwise — then outputs are all-gathered and statically unpermuted so
    every chip returns its natural contiguous shard. Collectives: 4-5
    all-gathers, no permute ring — budgeted under DTL151/DTL154 by the
    train.sp shard contract."""
    b, h, n_local, d = q.shape
    n = n_local * axis_size
    layout = plan.layout
    assert layout.n == n, (layout.n, n)
    scale = d**-0.5 if sm_scale is None else sm_scale
    idx = jax.lax.axis_index(axis_name)

    qf = jax.lax.all_gather(q, axis_name, axis=2, tiled=True)
    kf = jax.lax.all_gather(k, axis_name, axis=2, tiled=True)
    vf = jax.lax.all_gather(v, axis_name, axis=2, tiled=True)
    kmf = None
    if key_mask is not None:
        kmf = jax.lax.all_gather(key_mask, axis_name, axis=1, tiled=True)

    rows = jnp.asarray(plan.row_table)[idx]  # (rows_pc,)
    q_my = jnp.take(qf, rows, axis=2)

    if use_kernel:
        bh = b * h
        rows_pc = plan.rows_per_chip
        qk = _pad_rows(q_my.reshape(bh, rows_pc, d), rows_pc, 1)
        kk = _pad_rows(kf.reshape(bh, n, d), layout.n_pad, 1)
        vk = _pad_rows(vf.reshape(bh, n, d), layout.n_pad, 1)
        kmk = None
        if kmf is not None:
            kmk = _pad_rows(_bcast_key_mask(kmf, bh, h, n), layout.n_pad, 2)
        mask_i8 = jnp.asarray(plan.masks, jnp.int8)[idx]
        o_my = _pair_attention(
            qk, kk, vk, kmk, mask_i8,
            jnp.asarray(plan.fwd_tables)[idx],
            jnp.asarray(plan.kv_tables)[idx],
            scale, layout.block_q, layout.block_k, interpret,
        ).reshape(b, h, rows_pc, d)
    else:
        from .attention import dense_attend

        allowed = jnp.asarray(plan.masks)[idx][:, :n][None, None]
        if kmf is not None:
            allowed = allowed & kmf[:, None, None, :]
        o_my = dense_attend(q_my * scale, kf, vf, allowed, stable)

    o_all = jax.lax.all_gather(o_my, axis_name, axis=2, tiled=True)
    o_nat = jnp.take(o_all, jnp.asarray(plan.inv_perm[:n]), axis=2)
    return jax.lax.dynamic_slice_in_dim(o_nat, idx * n_local, n_local, axis=2)
