"""Lazy g++ build for the native BPE engine.

The shared library is compiled on first use into the package directory (or
``DALLE_TPU_NATIVE_DIR``) and rebuilt only when the sources are newer —
the ctypes analog of setuptools' build_ext, without requiring an install
step. pybind11 is not part of this image; the engine exposes a plain C ABI.
"""

from __future__ import annotations

import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_SRC_DIR = Path(__file__).parent
_SOURCES = [_SRC_DIR / "bpe_tokenizer.cc"]
_HEADERS = [_SRC_DIR / "unicode_tables.h"]
_LOCK = threading.Lock()


def _out_dir() -> Path:
    d = os.environ.get("DALLE_TPU_NATIVE_DIR")
    if d:
        return Path(d)
    if os.access(_SRC_DIR, os.W_OK):
        return _SRC_DIR
    return Path.home() / ".cache" / "dalle_tpu" / "native"


def lib_path() -> Path:
    return _out_dir() / "libdalle_bpe.so"


def build(force: bool = False) -> Optional[Path]:
    """Compile (if stale) and return the .so path; None when no toolchain."""
    with _LOCK:
        so = lib_path()
        deps = _SOURCES + _HEADERS
        if (
            not force
            and so.exists()
            and so.stat().st_mtime >= max(p.stat().st_mtime for p in deps)
        ):
            return so
        so.parent.mkdir(parents=True, exist_ok=True)
        tmp = so.with_suffix(".so.tmp")
        cmd = [
            os.environ.get("CXX", "g++"),
            "-O2", "-std=c++17", "-shared", "-fPIC",
            *(str(s) for s in _SOURCES),
            "-o", str(tmp),
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True, timeout=300
            )
        except (OSError, subprocess.SubprocessError):
            return None
        os.replace(tmp, so)  # atomic: concurrent loaders never see a partial .so
        return so
