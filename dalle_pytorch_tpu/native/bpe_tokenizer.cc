// Native byte-level BPE tokenizer engine.
//
// TPU-native re-ownership of the reference's native tokenizer dependencies
// (SURVEY.md §2.3): the reference leans on HuggingFace `tokenizers` (Rust,
// tokenizer.py:158-192) and youtokentome (C++, tokenizer.py:232-266) for fast
// BPE, and vendors OpenAI's pure-Python CLIP tokenizer for the default vocab
// (tokenizer.py:20-154). This engine implements that CLIP byte-level BPE —
// scanner, merge loop, decoder — in C++ behind a C ABI consumed via ctypes
// (data/native_bpe.py), with byte-exact parity against the Python
// implementation (tests/test_native_bpe.py).
//
// Parity-critical details mirrored from data/tokenizers.py:
//  - the GPT-2/CLIP byte<->printable-codepoint bijection (bytes_to_unicode)
//    is inverted at load time so the merge loop runs in the raw-byte domain;
//  - vocab assembly order: 256 base chars (in bytes_to_unicode value order),
//    256 "</w>" variants, 48894 merges (file lines [1, 48895)), then
//    <|startoftext|>, <|endoftext|>  => 49408 ids;
//  - the scanner reproduces the regex alternation
//      <|sot|> | <|eot|> | 's|'t|'re|'ve|'m|'ll|'d | \p{L}+ | \p{N} |
//      [^\s\p{L}\p{N}]+
//    with leftmost first-alternative semantics (NOT longest-match), using
//    classification tables generated from the Python `regex` module itself
//    (gen_unicode_tables.py);
//  - the merge pass copies the reference's exact in-word scan semantics
//    (word.index(first, i) / overlap handling, tokenizer.py:98-115 of the
//    reference == data/tokenizers.py:178-197 here).

#include <cstdint>
#include <cstring>
#include <fstream>
#include <mutex>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

#include "unicode_tables.h"

namespace {

// ------------------------------------------------------------- classification

bool in_ranges(uint32_t cp, const CpRange* ranges, int n) {
  int lo = 0, hi = n - 1;
  while (lo <= hi) {
    int mid = (lo + hi) / 2;
    if (cp < ranges[mid].lo) {
      hi = mid - 1;
    } else if (cp > ranges[mid].hi) {
      lo = mid + 1;
    } else {
      return true;
    }
  }
  return false;
}

bool is_letter(uint32_t cp) { return in_ranges(cp, kLetterRanges, kLetterRanges_len); }
bool is_number(uint32_t cp) { return in_ranges(cp, kNumberRanges, kNumberRanges_len); }
bool is_other(uint32_t cp) { return in_ranges(cp, kOtherRanges, kOtherRanges_len); }

// ---------------------------------------------------------------------- utf-8

// Decodes the codepoint at s[i]; advances i past it. Invalid bytes decode as
// 0xFFFD and advance by one (the scanner then treats them as "other").
uint32_t utf8_next(const std::string& s, size_t& i) {
  uint8_t b0 = s[i];
  if (b0 < 0x80) { i += 1; return b0; }
  int extra; uint32_t cp;
  if ((b0 & 0xE0) == 0xC0) { extra = 1; cp = b0 & 0x1F; }
  else if ((b0 & 0xF0) == 0xE0) { extra = 2; cp = b0 & 0x0F; }
  else if ((b0 & 0xF8) == 0xF0) { extra = 3; cp = b0 & 0x07; }
  else { i += 1; return 0xFFFD; }
  if (i + (size_t)extra >= s.size()) { i += 1; return 0xFFFD; }
  for (int k = 1; k <= extra; ++k) {
    if ((s[i + k] & 0xC0) != 0x80) { i += 1; return 0xFFFD; }
    cp = (cp << 6) | (s[i + k] & 0x3F);
  }
  i += extra + 1;
  return cp;
}

void utf8_append(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out += (char)cp;
  } else if (cp < 0x800) {
    out += (char)(0xC0 | (cp >> 6));
    out += (char)(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    out += (char)(0xE0 | (cp >> 12));
    out += (char)(0x80 | ((cp >> 6) & 0x3F));
    out += (char)(0x80 | (cp & 0x3F));
  } else {
    out += (char)(0xF0 | (cp >> 18));
    out += (char)(0x80 | ((cp >> 12) & 0x3F));
    out += (char)(0x80 | ((cp >> 6) & 0x3F));
    out += (char)(0x80 | (cp & 0x3F));
  }
}

// ------------------------------------------------------------------- engine

struct Engine {
  // byte <-> remapped-codepoint bijection (bytes_to_unicode)
  uint32_t byte_to_cp[256];
  std::unordered_map<uint32_t, uint8_t> cp_to_byte;

  // interned symbols: raw bytes + end-of-word flag
  std::vector<std::string> sym_bytes;
  std::vector<uint8_t> sym_eow;
  std::vector<int32_t> sym_vocab;
  std::unordered_map<std::string, int32_t> sym_index;  // key: bytes + '\x01' eow

  // (left_sym, right_sym) -> {rank, merged_sym}
  struct Merge { int32_t rank, merged; };
  std::unordered_map<uint64_t, Merge> merges;

  // vocab id -> raw byte string ("</w>" and special tokens literal)
  std::vector<std::string> vocab_bytes;
  int32_t sot_id = -1, eot_id = -1;

  std::unordered_map<std::string, std::vector<int32_t>> cache;
  std::mutex cache_mu;

  std::string error;

  int32_t intern(const std::string& bytes, bool eow, int32_t vocab_id) {
    std::string key = bytes;
    key += eow ? '\x01' : '\x00';
    auto it = sym_index.find(key);
    if (it != sym_index.end()) {
      if (vocab_id >= 0 && sym_vocab[it->second] < 0) sym_vocab[it->second] = vocab_id;
      return it->second;
    }
    int32_t id = (int32_t)sym_bytes.size();
    sym_bytes.push_back(bytes);
    sym_eow.push_back(eow ? 1 : 0);
    sym_vocab.push_back(vocab_id);
    sym_index.emplace(std::move(key), id);
    return id;
  }

  // remapped-domain symbol text -> (raw bytes, eow)
  bool parse_symbol(const std::string& text, std::string* bytes, bool* eow) {
    std::string t = text;
    *eow = false;
    if (t.size() >= 4 && t.compare(t.size() - 4, 4, "</w>") == 0) {
      *eow = true;
      t = t.substr(0, t.size() - 4);
    }
    bytes->clear();
    size_t i = 0;
    while (i < t.size()) {
      uint32_t cp = utf8_next(t, i);
      auto it = cp_to_byte.find(cp);
      if (it == cp_to_byte.end()) return false;
      *bytes += (char)it->second;
    }
    return true;
  }

  bool load(const char* merges_path) {
    // bytes_to_unicode: printable ranges map to themselves, the rest to
    // 256+n in increasing byte order (data/tokenizers.py:59-75)
    std::vector<int> bs;
    for (int b = '!'; b <= '~'; ++b) bs.push_back(b);
    for (int b = 0xA1; b <= 0xAC; ++b) bs.push_back(b);
    for (int b = 0xAE; b <= 0xFF; ++b) bs.push_back(b);
    std::vector<bool> present(256, false);
    for (int b : bs) present[b] = true;
    std::vector<uint32_t> cs(bs.begin(), bs.end());
    int n = 0;
    for (int b = 0; b < 256; ++b) {
      if (!present[b]) {
        bs.push_back(b);
        cs.push_back(256 + n++);
      }
    }
    for (size_t i = 0; i < bs.size(); ++i) {
      byte_to_cp[bs[i]] = cs[i];
      cp_to_byte[cs[i]] = (uint8_t)bs[i];
    }

    // base vocab: 256 chars in bytes_to_unicode VALUE order, then "</w>"s
    vocab_bytes.resize(512);
    for (size_t i = 0; i < bs.size(); ++i) {
      std::string raw(1, (char)bs[i]);
      intern(raw, false, (int32_t)i);
      vocab_bytes[i] = raw;
    }
    for (size_t i = 0; i < bs.size(); ++i) {
      std::string raw(1, (char)bs[i]);
      intern(raw, true, (int32_t)(256 + i));
      vocab_bytes[256 + i] = raw + "</w>";
    }

    std::ifstream f(merges_path, std::ios::binary);
    if (!f) { error = "cannot open merges file"; return false; }
    std::string line;
    std::vector<std::string> lines;
    while (std::getline(f, line)) lines.push_back(line);
    // reference slicing: merges = lines[1 : 49152-256-2+1]
    size_t lo = 1, hi = std::min<size_t>(lines.size(), 49152 - 256 - 2 + 1);
    int32_t rank = 0;
    for (size_t li = lo; li < hi; ++li, ++rank) {
      const std::string& ln = lines[li];
      size_t sp = ln.find(' ');
      if (sp == std::string::npos) { error = "bad merge line"; return false; }
      std::string s1 = ln.substr(0, sp), s2 = ln.substr(sp + 1);
      // strip trailing \r (file is \n separated; be safe)
      while (!s2.empty() && (s2.back() == '\r' || s2.back() == ' ')) s2.pop_back();
      std::string b1, b2;
      bool e1, e2;
      if (!parse_symbol(s1, &b1, &e1) || !parse_symbol(s2, &b2, &e2)) {
        error = "unparseable merge symbol at line " + std::to_string(li);
        return false;
      }
      int32_t l = intern(b1, e1, -1);
      int32_t r = intern(b2, e2, -1);
      int32_t vocab_id = 512 + rank;
      int32_t merged = intern(b1 + b2, e2, vocab_id);
      vocab_bytes.push_back(b1 + b2 + (e2 ? "</w>" : ""));
      merges.emplace(((uint64_t)(uint32_t)l << 32) | (uint32_t)r,
                     Merge{rank, merged});
    }
    sot_id = (int32_t)vocab_bytes.size();
    vocab_bytes.push_back("<|startoftext|>");
    eot_id = (int32_t)vocab_bytes.size();
    vocab_bytes.push_back("<|endoftext|>");
    return true;
  }

  // ---------------------------------------------------------------- bpe core

  void bpe_word(std::vector<int32_t>& w) {
    while (w.size() > 1) {
      int32_t best_rank = INT32_MAX, first = -1, second = -1, merged = -1;
      for (size_t i = 0; i + 1 < w.size(); ++i) {
        auto it = merges.find(((uint64_t)(uint32_t)w[i] << 32) | (uint32_t)w[i + 1]);
        if (it != merges.end() && it->second.rank < best_rank) {
          best_rank = it->second.rank;
          first = w[i];
          second = w[i + 1];
          merged = it->second.merged;
        }
      }
      if (first < 0) break;
      // reference merge-pass semantics (word.index(first, i) scan)
      std::vector<int32_t> out;
      out.reserve(w.size());
      size_t i = 0;
      while (i < w.size()) {
        size_t j = i;
        while (j < w.size() && w[j] != first) ++j;
        if (j == w.size()) {
          out.insert(out.end(), w.begin() + i, w.end());
          break;
        }
        out.insert(out.end(), w.begin() + i, w.begin() + j);
        i = j;
        if (i + 1 < w.size() && w[i] == first && w[i + 1] == second) {
          out.push_back(merged);
          i += 2;
        } else {
          out.push_back(w[i]);
          i += 1;
        }
      }
      w.swap(out);
    }
  }

  void encode_token(const std::string& tok, std::vector<int32_t>* out) {
    {
      std::lock_guard<std::mutex> g(cache_mu);
      auto it = cache.find(tok);
      if (it != cache.end()) {
        out->insert(out->end(), it->second.begin(), it->second.end());
        return;
      }
    }
    std::vector<int32_t> w;
    w.reserve(tok.size());
    for (size_t i = 0; i < tok.size(); ++i) {
      std::string key(1, tok[i]);
      key += (i + 1 == tok.size()) ? '\x01' : '\x00';
      w.push_back(sym_index.at(key));
    }
    bpe_word(w);
    std::vector<int32_t> ids;
    ids.reserve(w.size());
    for (int32_t s : w) ids.push_back(sym_vocab[s]);
    out->insert(out->end(), ids.begin(), ids.end());
    std::lock_guard<std::mutex> g(cache_mu);
    cache.emplace(tok, std::move(ids));
  }

  // --------------------------------------------------------------- scanner

  static bool starts_with(const std::string& s, size_t i, const char* lit) {
    size_t n = std::strlen(lit);
    return s.size() - i >= n && s.compare(i, n, lit) == 0;
  }

  // Case-insensitive equality with a contraction letter, matching the regex
  // module's IGNORECASE closure exactly: ASCII case pair, plus U+017F (long
  // s) which case-folds to 's' (verified against regex.fullmatch over all
  // codepoints — only 's' has a non-ASCII equivalent).
  static bool cp_eq(uint32_t cp, char c) {
    return cp == (uint32_t)c || cp == (uint32_t)(c - 32) ||
           (c == 's' && cp == 0x17F);
  }

  // Byte length of a contraction match ('s|'t|'re|'ve|'m|'ll|'d) starting at
  // the apostrophe at text[i]; 0 when none matches.
  size_t match_contraction(const std::string& text, size_t i) {
    size_t p = i + 1;
    if (p >= text.size()) return 0;
    size_t q1 = p;
    uint32_t c1 = utf8_next(text, q1);
    if (cp_eq(c1, 's') || cp_eq(c1, 't') || cp_eq(c1, 'm') || cp_eq(c1, 'd')) {
      return q1 - i;
    }
    if (q1 >= text.size()) return 0;
    size_t q2 = q1;
    uint32_t c2 = utf8_next(text, q2);
    if ((cp_eq(c1, 'r') && cp_eq(c2, 'e')) ||
        (cp_eq(c1, 'v') && cp_eq(c2, 'e')) ||
        (cp_eq(c1, 'l') && cp_eq(c2, 'l'))) {
      return q2 - i;
    }
    return 0;
  }

  void encode_text(const std::string& text, std::vector<int32_t>* out) {
    size_t i = 0;
    while (i < text.size()) {
      if (starts_with(text, i, "<|startoftext|>")) {
        out->push_back(sot_id);
        i += 15;
        continue;
      }
      if (starts_with(text, i, "<|endoftext|>")) {
        out->push_back(eot_id);
        i += 13;
        continue;
      }
      if (text[i] == '\'') {
        size_t n = match_contraction(text, i);
        if (n) {
          encode_token(text.substr(i, n), out);
          i += n;
          continue;
        }
      }
      size_t start = i;
      size_t peek = i;
      uint32_t cp = utf8_next(text, peek);
      if (is_letter(cp)) {  // [\p{L}]+
        i = peek;
        while (i < text.size()) {
          size_t nx = i;
          uint32_t c2 = utf8_next(text, nx);
          if (!is_letter(c2)) break;
          i = nx;
        }
        encode_token(text.substr(start, i - start), out);
        continue;
      }
      if (is_number(cp)) {  // [\p{N}] (single codepoint)
        i = peek;
        encode_token(text.substr(start, i - start), out);
        continue;
      }
      if (is_other(cp)) {
        // [^\s\p{L}\p{N}]+ — runs through special tokens/apostrophes too,
        // exactly like the regex alternation does mid-run
        i = peek;
        while (i < text.size()) {
          size_t nx = i;
          uint32_t c2 = utf8_next(text, nx);
          if (!is_other(c2)) break;
          i = nx;
        }
        encode_token(text.substr(start, i - start), out);
        continue;
      }
      // matches no alternative (whitespace, or case-closure gaps like
      // U+0345): findall skips it
      i = peek;
    }
  }

  // ---------------------------------------------------------------- decode

  std::string decode_ids(const int32_t* ids, int64_t n, const int32_t* skip,
                         int64_t n_skip) {
    std::string raw;
    for (int64_t i = 0; i < n; ++i) {
      int32_t id = ids[i];
      if (id == 0 || id < 0 || id >= (int32_t)vocab_bytes.size()) continue;
      bool skipped = false;
      for (int64_t k = 0; k < n_skip; ++k) {
        if (skip[k] == id) { skipped = true; break; }
      }
      if (!skipped) raw += vocab_bytes[id];
    }
    // utf-8 validate with U+FFFD replacement (python errors="replace")
    std::string valid;
    valid.reserve(raw.size());
    size_t i = 0;
    while (i < raw.size()) {
      size_t before = i;
      uint32_t cp = utf8_next(raw, i);
      if (cp == 0xFFFD && raw.compare(before, i - before, "\xEF\xBF\xBD") != 0) {
        valid += "\xEF\xBF\xBD";
      } else {
        valid.append(raw, before, i - before);
      }
    }
    // "</w>" -> " "
    std::string out;
    out.reserve(valid.size());
    i = 0;
    while (i < valid.size()) {
      if (starts_with(valid, i, "</w>")) {
        out += ' ';
        i += 4;
      } else {
        out += valid[i++];
      }
    }
    return out;
  }
};

}  // namespace

// ------------------------------------------------------------------- C ABI

extern "C" {

void* bpe_new(const char* merges_path) {
  auto* e = new Engine();
  if (!e->load(merges_path)) {
    delete e;
    return nullptr;
  }
  return e;
}

void bpe_free(void* h) { delete (Engine*)h; }

int32_t bpe_vocab_size(void* h) {
  return (int32_t)((Engine*)h)->vocab_bytes.size();
}

// Encodes UTF-8 text; writes up to max_out ids; returns the total id count
// (callers grow the buffer and retry when the return exceeds max_out).
int64_t bpe_encode(void* h, const char* text, int64_t text_len, int32_t* out,
                   int64_t max_out) {
  std::vector<int32_t> ids;
  ((Engine*)h)->encode_text(std::string(text, (size_t)text_len), &ids);
  int64_t n = (int64_t)ids.size();
  for (int64_t i = 0; i < std::min(n, max_out); ++i) out[i] = ids[i];
  return n;
}

// Decodes ids (skipping `skip` ids and 0); returns byte count written
// (retry with a larger buffer if it exceeds max_out).
int64_t bpe_decode(void* h, const int32_t* ids, int64_t n, const int32_t* skip,
                   int64_t n_skip, char* out, int64_t max_out) {
  std::string s = ((Engine*)h)->decode_ids(ids, n, skip, n_skip);
  int64_t len = (int64_t)s.size();
  for (int64_t i = 0; i < std::min(len, max_out); ++i) out[i] = s[i];
  return len;
}

}  // extern "C"
