"""dalle_pytorch_tpu — a TPU-native text-to-image framework.

Brand-new JAX/XLA/Pallas implementation of the capability surface of
DALLE-pytorch (studied at /root/reference): discrete VAEs, the DALL-E
autoregressive text+image transformer with full/axial/conv/block-sparse
attention, CLIP reranking, tokenizers, data pipelines, and a device-mesh
parallelism runtime replacing the reference's DeepSpeed/Horovod backends.
"""

__version__ = "0.1.0"
