"""Rank-aware download/cache utility.

Re-owns the reference's ``download`` (vae.py:53-94): files land in a local
cache directory; only the per-host root process downloads while other local
ranks wait at a barrier, preventing N processes from fetching the same
checkpoint. On TPU pods JAX runs one process per host, so the local-root race
is rare — the coordination hook stays for multi-process-per-host setups.
"""

from __future__ import annotations

import os
import shutil
import urllib.request
from pathlib import Path
from typing import Optional

CACHE_DIR = os.path.expanduser("~/.cache/dalle_tpu")


def download(
    url: str,
    filename: Optional[str] = None,
    root: str = CACHE_DIR,
    runtime=None,
) -> str:
    """Fetch ``url`` into ``root`` (once per host) and return the local path.

    ``runtime`` (a MeshRuntime) gates the fetch to the local root worker and
    barriers the rest — the reference's local_barrier dance (vae.py:67-74).
    """
    filename = filename or url.split("/")[-1]
    path = Path(root) / filename
    if path.exists():
        return str(path)

    is_local_root = runtime is None or runtime.is_local_root_worker()
    if is_local_root:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        if url.startswith(("http://", "https://")):
            with urllib.request.urlopen(url) as r, open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
        else:  # local/NFS path "url"s work too (common on pods)
            shutil.copyfile(url, tmp)
        tmp.replace(path)
    if runtime is not None:
        runtime.barrier()  # non-roots wait for the file to appear
    assert path.exists(), f"download of {url} failed"
    return str(path)
