"""Rank-aware download/cache utility.

Re-owns the reference's ``download`` (vae.py:53-94): files land in a local
cache directory; only the per-host root process downloads while other local
ranks wait at a barrier, preventing N processes from fetching the same
checkpoint. On TPU pods JAX runs one process per host, so the local-root race
is rare — the coordination hook stays for multi-process-per-host setups.

Resilience (docs/DESIGN.md §9): the reference's single unguarded ``urlopen``
(no timeout, stale ``.tmp`` left behind on crash) becomes a retried fetch
with exponential backoff (``DALLE_TPU_DOWNLOAD_RETRIES`` /
``DALLE_TPU_DOWNLOAD_BACKOFF`` override the policy), a socket timeout, and
``.tmp`` cleanup on entry and on every failure — a crashed fetch can't wedge
every later run. Retries/failures are tallied in ``metrics.counters``;
failures are injectable via the ``download`` fault site.
"""

from __future__ import annotations

import os
import shutil
import urllib.error
import urllib.request
from pathlib import Path
from typing import Optional

from .faults import FAULTS
from .metrics import counters
from .resilience import RetryPolicy, retry

CACHE_DIR = os.path.expanduser("~/.cache/dalle_tpu")

DOWNLOAD_RETRY = RetryPolicy(
    attempts=3,
    base_delay=0.5,
    retry_on=(urllib.error.URLError, TimeoutError, OSError),
)


def download(
    url: str,
    filename: Optional[str] = None,
    root: str = CACHE_DIR,
    runtime=None,
    timeout: Optional[float] = 60.0,
    policy: Optional[RetryPolicy] = None,
) -> str:
    """Fetch ``url`` into ``root`` (once per host) and return the local path.

    ``runtime`` (a MeshRuntime) gates the fetch to the local root worker and
    barriers the rest — the reference's local_barrier dance (vae.py:67-74).
    ``timeout`` is the per-connection socket timeout handed to ``urlopen``
    (``DALLE_TPU_DOWNLOAD_TIMEOUT`` overrides).
    """
    filename = filename or url.split("/")[-1]
    path = Path(root) / filename
    if path.exists():
        return str(path)

    env_timeout = os.environ.get("DALLE_TPU_DOWNLOAD_TIMEOUT")
    if env_timeout is not None:
        timeout = float(env_timeout)  # timeout=None (no limit) stays valid
    policy = (policy or DOWNLOAD_RETRY).from_env("DALLE_TPU_DOWNLOAD")
    tmp = path.with_suffix(path.suffix + ".tmp")

    is_local_root = runtime is None or runtime.is_local_root_worker()
    if is_local_root:
        path.parent.mkdir(parents=True, exist_ok=True)
        if tmp.exists():  # stale leftover from a crashed earlier run
            tmp.unlink()

        def fetch():
            FAULTS.maybe_raise(
                "download", urllib.error.URLError("injected download fault")
            )
            if url.startswith(("http://", "https://")):
                with urllib.request.urlopen(url, timeout=timeout) as r, \
                        open(tmp, "wb") as f:
                    shutil.copyfileobj(r, f)
            else:  # local/NFS path "url"s work too (common on pods)
                shutil.copyfile(url, tmp)
            tmp.replace(path)

        def cleanup(attempt, exc):
            counters.inc("download.retries")
            tmp.unlink(missing_ok=True)  # never leave a torn partial fetch

        try:
            retry(fetch, policy, describe=f"download {url}", on_retry=cleanup)
        except policy.retry_on:
            counters.inc("download.failures")
            tmp.unlink(missing_ok=True)  # final attempt's torn partial
            raise
    if runtime is not None:
        runtime.barrier()  # non-roots wait for the file to appear
    assert path.exists(), f"download of {url} failed"
    return str(path)
