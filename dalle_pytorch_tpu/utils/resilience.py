"""Resilience layer: retry policies, preemption handling, and directory
manifests — the host-side half of fault tolerance (the device-side half,
the NaN step guard, lives in ``parallel/step.py``).

Failure model (docs/DESIGN.md §8): on preemptible TPU pods the faults
that actually occur are (a) host preemption mid-epoch (SIGTERM with a
short grace window), (b) torn checkpoint dirs from a crash mid-save,
(c) transient network failures on downloads and shard streams, and
(d) non-finite losses from numerics or bad batches. Each gets one
mechanism here, each injectable via ``utils.faults`` so tests exercise
the real code path deterministically on CPU.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import signal
import sys
import time
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Callable, Optional, Tuple, Type

MANIFEST_NAME = "MANIFEST.json"
COMMIT_NAME = "COMMITTED"


# --------------------------------------------------------------- retry


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter: attempt i (0-based) sleeps
    ``min(max_delay, base_delay * 2**i) * uniform(1-jitter, 1)``."""

    attempts: int = 3
    base_delay: float = 0.5
    max_delay: float = 30.0
    jitter: float = 0.5
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    def delay(self, attempt: int, rng: Optional[random.Random] = None,
              ) -> float:
        """Backoff before retry number ``attempt`` (0-based): the one
        formula every ladder shares — ``retry()`` below, the router's
        breaker/respawn ladders, and traffic-sim clients. With ``rng``
        None the jitter factor is omitted (the deterministic upper
        envelope); pass a seeded ``random.Random`` to draw full jitter —
        callers that need replayable schedules own the RNG."""
        d = min(self.max_delay, self.base_delay * (2 ** attempt))
        if rng is not None and self.jitter > 0.0:
            d *= 1.0 - self.jitter * rng.random()
        return d

    def from_env(self, prefix: str) -> "RetryPolicy":
        """Override attempts/base_delay from ``<PREFIX>_RETRIES`` /
        ``<PREFIX>_BACKOFF`` (operators tune retry budgets per deployment
        without code changes; docs/DESIGN.md §8 lists the knobs)."""
        out = self
        retries = os.environ.get(f"{prefix}_RETRIES")
        if retries is not None:
            out = replace(out, attempts=int(retries))
        backoff = os.environ.get(f"{prefix}_BACKOFF")
        if backoff is not None:
            out = replace(out, base_delay=float(backoff))
        return out


def retry_after_hint(occupancy: float, base_delay: float = 0.5,
                     max_delay: float = 30.0) -> float:
    """Server-side backoff hint for a load-typed rejection
    (``RequestResult.retry_after_s``): scale the ladder's base delay by
    how loaded the fleet is — an idle fleet says "come right back", a
    saturated one says "wait out ~one ladder rung". Linear in occupancy
    (hint = base * (1 + 4*occ), clamped to ``max_delay``) so the hint
    stays proportional to the pressure that caused the reject; clients
    spread over [0, hint] via their own jitter, the hint is the center
    of mass, not a synchronization point."""
    occ = min(1.0, max(0.0, occupancy))
    return min(max_delay, base_delay * (1.0 + 4.0 * occ))


def retry(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    describe: str = "",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
):
    """Call ``fn()`` up to ``policy.attempts`` times; re-raise the last
    error once exhausted. ``on_retry(attempt, exc)`` runs before each
    backoff — i.e. only when another attempt follows, so it counts actual
    retries; final-failure cleanup belongs in the caller's except. ``sleep``
    and ``rng`` are injectable so tests assert the backoff schedule
    without wall-clock waits."""
    rng = rng or random.Random()
    attempts = max(1, policy.attempts)  # "0 retries" still means one attempt
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except policy.retry_on as e:
            last = e
            if attempt == attempts - 1:
                break
            if on_retry is not None:
                on_retry(attempt, e)
            delay = policy.delay(attempt, rng)
            print(
                f"retry {attempt + 1}/{attempts} "
                f"{describe or getattr(fn, '__name__', 'call')}: "
                f"{type(e).__name__}: {e} (backoff {delay:.2f}s)",
                file=sys.stderr,
            )
            if delay > 0:
                sleep(delay)
    assert last is not None
    raise last


# ---------------------------------------------------------- preemption


class PreemptionHandler:
    """Convert SIGTERM/SIGINT into a flag the training loop polls.

    Preemptible TPU hosts get SIGTERM with a short grace window; the loop
    finishes the in-flight step, writes an emergency step-granular
    checkpoint, and exits cleanly (train_dalle.py). The first signal only
    sets the flag; a second raises ``KeyboardInterrupt`` so a stuck save
    can still be interrupted by hand. Use as a context manager —
    original handlers are restored on exit.

    ``on_signal(signum)`` runs inside the first signal's handler — the
    flight-recorder drain hook (utils/telemetry.py): even if the loop
    never reaches its emergency save (stuck step, hung collective), the
    telemetry ring is already on disk. It must be cheap and is called
    FAIL-OPEN: an exception is printed and swallowed, because a broken
    observability hook must never turn a clean preemption into a crash."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_signal: Optional[Callable[[int], None]] = None):
        self.signals = signals
        self.on_signal = on_signal
        self.triggered = False
        self.signum: Optional[int] = None
        self._old = {}

    def _handle(self, signum, frame):
        if self.triggered:
            raise KeyboardInterrupt(f"second signal {signum} during shutdown")
        self.triggered = True
        self.signum = signum
        print(
            f"signal {signum} received: finishing step, saving emergency "
            "checkpoint, exiting",
            file=sys.stderr,
        )
        if self.on_signal is not None:
            try:
                self.on_signal(signum)
            except Exception as e:  # fail open: observability never kills
                print(
                    f"on_signal hook failed (ignored): "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    def __enter__(self) -> "PreemptionHandler":
        for s in self.signals:
            self._old[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old.clear()
        return False


# --------------------------------------------------- directory manifests


def _sha256(path: Path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def write_dir_manifest(dirpath: str, extra: Optional[dict] = None) -> None:
    """Checksum every file under ``dirpath`` into MANIFEST.json, then
    write the COMMITTED marker (atomically, last) — the two-phase commit
    for directory checkpoints. A crash at ANY point leaves either no
    marker (torn save, skipped by readers) or a fully verifiable dir."""
    root = Path(dirpath)
    files = {}
    for p in sorted(root.rglob("*")):
        if not p.is_file() or p.name in (MANIFEST_NAME, COMMIT_NAME):
            continue
        rel = p.relative_to(root).as_posix()
        files[rel] = {"sha256": _sha256(p), "bytes": p.stat().st_size}
    manifest = {"files": files, **(extra or {})}
    mpath = root / MANIFEST_NAME
    tmp = mpath.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(manifest, indent=1, sort_keys=True))
    tmp.replace(mpath)
    ctmp = root / (COMMIT_NAME + ".tmp")
    ctmp.write_text("ok\n")
    ctmp.replace(root / COMMIT_NAME)


FILE_MANIFEST_SUFFIX = ".manifest.json"


def write_file_manifest(path: str) -> None:
    """Sidecar manifest for a SINGLE-file artifact (the plain msgpack
    checkpoint format): ``<path>.manifest.json`` holding sha256 + byte
    size, written atomically AFTER the artifact itself — the single-file
    analog of the directory two-phase commit. A crash between the artifact
    replace and the sidecar write leaves an unverified (not poisoned)
    file; readers distinguish "no manifest" from "manifest mismatch"."""
    p = Path(path)
    manifest = {"sha256": _sha256(p), "bytes": p.stat().st_size}
    mpath = Path(str(p) + FILE_MANIFEST_SUFFIX)
    tmp = mpath.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(manifest, indent=1, sort_keys=True))
    tmp.replace(mpath)


def verify_file_manifest(path: str) -> Tuple[bool, str]:
    """-> (ok, reason). ``reason`` is ``"no manifest"`` when the sidecar is
    absent (a pre-manifest artifact — callers decide whether unverified is
    acceptable), otherwise names the failure: size drift (torn write) or
    checksum mismatch (bit corruption)."""
    p = Path(path)
    if not p.exists():
        return False, "file missing"
    mpath = Path(str(p) + FILE_MANIFEST_SUFFIX)
    if not mpath.exists():
        return False, "no manifest"
    try:
        manifest = json.loads(mpath.read_text())
        want_sha, want_bytes = manifest["sha256"], manifest["bytes"]
    except (ValueError, KeyError) as e:
        return False, f"unreadable manifest: {e}"
    if p.stat().st_size != want_bytes:
        return False, (
            f"size mismatch: {p.stat().st_size} != {want_bytes} (torn write)"
        )
    if _sha256(p) != want_sha:
        return False, "checksum mismatch (bit corruption)"
    return True, "ok"


def verify_dir_manifest(dirpath: str) -> Tuple[bool, str]:
    """-> (ok, reason). Unverified means: no commit marker (torn save),
    no/unreadable manifest, a listed file missing, size drift, or a
    checksum mismatch (bit corruption). Extra unlisted files are allowed
    (a writer may leave scratch); everything the manifest names must
    verify."""
    root = Path(dirpath)
    if not (root / COMMIT_NAME).exists():
        return False, "no commit marker (torn or in-progress save)"
    mpath = root / MANIFEST_NAME
    if not mpath.exists():
        return False, "commit marker without manifest"
    try:
        manifest = json.loads(mpath.read_text())
        files = manifest["files"]
    except (ValueError, KeyError) as e:
        return False, f"unreadable manifest: {e}"
    for rel, spec in files.items():
        p = root / rel
        if not p.exists():
            return False, f"missing file {rel}"
        if p.stat().st_size != spec["bytes"]:
            return False, f"size mismatch {rel}"
        if _sha256(p) != spec["sha256"]:
            return False, f"checksum mismatch {rel}"
    return True, "ok"
