"""Metrics sink: console + optional Weights & Biases, root-rank-guarded.

Mirrors the reference's observability surface (SURVEY.md §5.5): per-step
loss/lr logs (train_dalle.py:589-599), throughput as ``sample_per_sec``
computed over 10-step windows (train_dalle.py:568-569,621-624), periodic
sample images, and run config capture — with wandb optional (gated import)
instead of required, and an MFU gauge the reference lacks.
"""

from __future__ import annotations

import bisect
import json
import math
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# ------------------------------------------------------------------ labels
#
# Every registry below supports Prometheus-style labels: a series is
# (name, labels) — ``serve.pool_occupancy{replica="1"}`` — not a
# string-concatenated metric name. Callers either pass ``labels={...}``
# per call or bind them once with ``child(labels)``, which returns a view
# with the same mutating API (the serving engine binds ``replica=<id>``
# so one router run yields per-replica series without touching any call
# site). ``child(None)`` returns the registry itself, so the unlabeled
# path pays nothing.

LabelSet = Tuple[Tuple[str, str], ...]


def _labelset(labels: Optional[Dict[str, Any]]) -> LabelSet:
    """Canonical (sorted, stringified) form — the dict-key half of a
    series identity."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def render_series(name: str, labelset: LabelSet) -> str:
    """Human/snapshot rendering: ``name{k="v",...}`` (bare name when
    unlabeled) — matches the Prometheus exposition sample syntax."""
    if not labelset:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labelset)
    return f"{name}{{{inner}}}"


class _ChildView:
    """A registry view with labels pre-bound. Forwards every call with the
    bound labels merged under any per-call labels (call-site wins on key
    collision). Children of children compose."""

    def __init__(self, base, labels: Dict[str, Any]):
        self._base = base
        self._labels = {str(k): str(v) for k, v in labels.items()}

    def _merge(self, labels: Optional[Dict[str, Any]]) -> Dict[str, str]:
        if not labels:
            return self._labels
        return {**self._labels, **{str(k): str(v) for k, v in labels.items()}}

    def child(self, labels: Optional[Dict[str, Any]] = None):
        if not labels:
            return self
        return _ChildView(self._base, self._merge(labels))

    # forwarded API (whichever of these the base registry has)
    def inc(self, name, n=1, labels=None):
        return self._base.inc(name, n, labels=self._merge(labels))

    def set(self, name, value, labels=None):
        return self._base.set(name, value, labels=self._merge(labels))

    def observe(self, name, value, labels=None, **kw):
        return self._base.observe(name, value, labels=self._merge(labels), **kw)

    def get(self, name, *a, labels=None, **kw):
        return self._base.get(name, *a, labels=self._merge(labels), **kw)


class Counters:
    """Process-wide named counters for fault accounting (docs/DESIGN.md §9).

    Data-path degradation (skipped samples, quarantined shards, download
    retries) must be COUNTED, not just warned about — a run that silently
    dropped 30% of its shards looks healthy in the loss curve. Producers
    (data/webdata.py, utils/download.py) ``inc`` from loader threads;
    the trainer snapshots into the step metrics. Thread-safe; the
    ``_GUARDED_BY`` table is the machine-checked contract (tools/lint.py
    DTL051, docs/DESIGN.md §11)."""

    _GUARDED_BY = {"_lock": ("_counts",)}

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, LabelSet], int] = {}

    def inc(self, name: str, n: int = 1,
            labels: Optional[Dict[str, Any]] = None) -> int:
        key = (name, _labelset(labels))
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
            return self._counts[key]

    def get(self, name: str, labels: Optional[Dict[str, Any]] = None) -> int:
        with self._lock:
            return self._counts.get((name, _labelset(labels)), 0)

    def total(self, name: str) -> int:
        """Sum over every label variant of ``name`` (the unlabeled series
        included) — the fleet aggregate of a per-replica counter."""
        with self._lock:
            return sum(v for (n, _), v in self._counts.items() if n == name)

    def child(self, labels: Optional[Dict[str, Any]] = None):
        return self if not labels else _ChildView(self, labels)

    def series(self, prefix: str = "") -> List[Tuple[str, LabelSet, int]]:
        """(name, labelset, value) triples — the exposition-layer view."""
        with self._lock:
            return sorted(
                (n, ls, v) for (n, ls), v in self._counts.items()
                if n.startswith(prefix)
            )

    def snapshot(self, prefix: str = "") -> Dict[str, int]:
        return {
            render_series(n, ls): v for n, ls, v in self.series(prefix)
        }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


counters = Counters()


class Gauges:
    """Process-wide named gauges (last value wins) — the level companion to
    ``Counters``. The serving engine publishes pool occupancy and queue/
    running depths here each scheduling pass so an operator dashboard (or a
    test) reads the engine's current pressure without reaching into it.
    Thread-safe for the same reason Counters is."""

    _GUARDED_BY = {"_lock": ("_values",)}

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, LabelSet], float] = {}

    def set(self, name: str, value: float,
            labels: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            self._values[(name, _labelset(labels))] = float(value)

    def get(self, name: str, default: float = 0.0,
            labels: Optional[Dict[str, Any]] = None) -> float:
        with self._lock:
            return self._values.get((name, _labelset(labels)), default)

    def child(self, labels: Optional[Dict[str, Any]] = None):
        return self if not labels else _ChildView(self, labels)

    def series(self, prefix: str = "") -> List[Tuple[str, LabelSet, float]]:
        with self._lock:
            return sorted(
                (n, ls, v) for (n, ls), v in self._values.items()
                if n.startswith(prefix)
            )

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        return {
            render_series(n, ls): v for n, ls, v in self.series(prefix)
        }

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


gauges = Gauges()


class Histogram:
    """Fixed log-spaced-bucket distribution metric — the percentile
    companion to ``Counters``/``Gauges`` (docs/DESIGN.md §9).

    Request latency, queue wait, step time, and data-wait are
    distributions, not levels: a mean hides the p99 that pages an
    operator. Buckets are log-spaced (``per_decade`` per factor of 10,
    spanning [lo, hi)) so one default geometry covers microsecond span
    overheads and hundred-second checkpoint saves with bounded relative
    error: a reported percentile is the upper bound of its value's
    bucket, so it is within one bucket factor (default 10^0.1 ~ 1.26x)
    of the true order statistic. count/sum/min/max are exact.

    Thread-safe; observation is a bisect + three adds (no allocation),
    cheap enough for the serving engine's per-iteration path.
    """

    _GUARDED_BY = {"_lock": ("_counts", "count", "sum", "min", "max")}

    def __init__(self, lo: float = 1e-6, hi: float = 1e3,
                 per_decade: int = 10):
        assert 0 < lo < hi and per_decade > 0
        n = int(math.ceil(per_decade * math.log10(hi / lo))) + 1
        # upper bucket bounds; values above bounds[-1] land in overflow
        self.bounds: List[float] = [
            lo * 10.0 ** (i / per_decade) for i in range(n)
        ]
        self._counts = [0] * (n + 1)  # +1: overflow (+Inf) bucket
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-th percentile value
        (Prometheus ``histogram_quantile`` convention, conservative
        direction). Overflow-bucket hits report the exact observed max."""
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q / 100.0 * self.count))
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                if i >= len(self.bounds):  # overflow
                    return self.max
                return min(self.bounds[i], self.max)
        return self.max  # unreachable; counts sum to self.count

    def snapshot(self) -> Dict[str, float]:
        # one lock hold for the whole snapshot: the old unlocked reads
        # could interleave with a concurrent observe() and report a count
        # that disagrees with its own percentiles (surfaced by DTL051
        # once Histogram declared its _GUARDED_BY table)
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": 0.0 if self.count == 0 else self.min,
                "max": 0.0 if self.count == 0 else self.max,
                "p50": self._percentile_locked(50),
                "p95": self._percentile_locked(95),
                "p99": self._percentile_locked(99),
            }

    def buckets(self) -> List[Tuple[float, int]]:
        """(upper_bound, CUMULATIVE count) pairs up to the last nonzero
        bucket, plus the (+Inf, total) terminator — the Prometheus
        ``_bucket{le=...}`` exposition shape."""
        with self._lock:
            return self._buckets_locked()

    def _buckets_locked(self) -> List[Tuple[float, int]]:
        out: List[Tuple[float, int]] = []
        cum = 0
        last_nonzero = max(
            (i for i, c in enumerate(self._counts) if c), default=-1
        )
        for i, c in enumerate(self._counts[: len(self.bounds)]):
            cum += c
            if i <= last_nonzero:
                out.append((self.bounds[i], cum))
        out.append((math.inf, self.count))
        return out

    def exposition(self) -> Dict[str, Any]:
        """Atomic snapshot for the Prometheus renderer: buckets, sum,
        count, and quantiles from ONE lock hold — a concurrent observe()
        between separate reads would otherwise render a ``_count`` that
        disagrees with its own ``le="+Inf"`` bucket (Prometheus requires
        them equal within a scrape)."""
        with self._lock:
            return {
                "buckets": self._buckets_locked(),
                "sum": self.sum,
                "count": self.count,
                "quantiles": {
                    q: self._percentile_locked(q) for q in (50, 95, 99)
                },
            }

    def checkpoint(self) -> "HistogramCheckpoint":
        """Freeze the cumulative state for later ``snapshot_delta``.

        The Prometheus series stays monotone — windowing is the READER's
        subtraction, never a reset of the producer's counters (resetting
        would corrupt every other consumer's rate() over the same
        series). One lock hold, so the checkpoint is internally
        consistent with itself."""
        with self._lock:
            return HistogramCheckpoint(
                counts=tuple(self._counts), count=self.count, sum=self.sum,
                max=self.max,
            )

    def snapshot_delta(
        self, prev: Optional["HistogramCheckpoint"] = None
    ) -> Dict[str, float]:
        """Windowed stats since ``prev`` (a ``checkpoint()``): count, sum,
        mean, p50/p95/p99 computed over the bucket-count DIFFERENCES, so
        sliding-window percentiles never require resetting the cumulative
        series. ``prev=None`` — or a checkpoint from a different bucket
        geometry, or one newer than the current state (the registry was
        reset) — degrades to the full lifetime window.

        Window percentiles inherit the bucket resolution: each is the
        upper bound of its delta bucket (overflow hits report the
        lifetime max, the only max the buckets retain)."""
        with self._lock:
            dc = list(self._counts)
            count, total = self.count, self.sum
            if prev is not None and len(prev.counts) == len(dc):
                cand = [c - p for c, p in zip(dc, prev.counts)]
                if min(cand, default=0) >= 0 and self.count >= prev.count:
                    dc = cand
                    count = self.count - prev.count
                    total = self.sum - prev.sum
            out = {"count": float(count), "sum": total,
                   "mean": total / count if count else 0.0}
            for q in (50, 95, 99):
                out[f"p{q}"] = self._rank_walk_locked(dc, count, q)
            return out

    def _rank_walk_locked(self, dc: List[int], count: int, q: float) -> float:
        if count <= 0:
            return 0.0
        rank = max(1, math.ceil(q / 100.0 * count))
        seen = 0
        for i, c in enumerate(dc):
            seen += c
            if seen >= rank:
                if i >= len(self.bounds):  # overflow
                    return self.max
                return min(self.bounds[i], self.max)
        return self.max  # unreachable; dc sums to count


class HistogramCheckpoint:
    """Immutable cumulative-state marker for ``Histogram.snapshot_delta``
    — counts tuple + count/sum/max frozen under one lock hold."""

    __slots__ = ("counts", "count", "sum", "max")

    def __init__(self, counts: Tuple[int, ...], count: int, sum: float,
                 max: float):
        self.counts = counts
        self.count = count
        self.sum = sum
        self.max = max


class GaugeRing:
    """Fixed-capacity ring of gauge samples — the sliding-window
    companion to ``Gauges`` for level metrics (occupancy, queue depth,
    iteration gap) whose last value alone cannot answer "over the recent
    window". Push is O(1) and allocation-free after warmup; ``window()``
    reduces the live samples in one lock hold. Old samples fall off by
    capacity, so the window length is measured in pushes (the vitals
    layer pushes once per engine iteration)."""

    _GUARDED_BY = {"_lock": ("_buf", "_next", "_filled")}

    def __init__(self, capacity: int = 64):
        assert capacity >= 1, capacity
        self.capacity = capacity
        self._lock = threading.Lock()
        self._buf: List[float] = [0.0] * capacity
        self._next = 0
        self._filled = 0

    def push(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._buf[self._next] = v
            self._next = (self._next + 1) % self.capacity
            if self._filled < self.capacity:
                self._filled += 1

    def values(self) -> List[float]:
        """Live samples, oldest first."""
        with self._lock:
            if self._filled < self.capacity:
                return self._buf[: self._filled]
            return self._buf[self._next:] + self._buf[: self._next]

    def window(self) -> Dict[str, float]:
        """count/last/mean/min/max over the live samples (one lock
        hold); all-zero when nothing has been pushed yet."""
        with self._lock:
            n = self._filled
            if n == 0:
                return {"count": 0.0, "last": 0.0, "mean": 0.0,
                        "min": 0.0, "max": 0.0}
            if n < self.capacity:
                live = self._buf[:n]
            else:
                live = self._buf
            return {
                "count": float(n),
                "last": self._buf[(self._next - 1) % self.capacity],
                "mean": sum(live) / n,
                "min": min(live),
                "max": max(live),
            }


class Histograms:
    """Process-wide named histograms, created on first observe — same
    registry shape as ``Counters``/``Gauges`` so producers never
    pre-declare. The span API (utils/telemetry.py) feeds ``<span>_s``
    duration histograms here automatically."""

    _GUARDED_BY = {"_lock": ("_hists",)}

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, LabelSet], Histogram] = {}

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, Any]] = None, **hist_kw) -> None:
        key = (name, _labelset(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(**hist_kw)
        h.observe(value)

    def get(self, name: str,
            labels: Optional[Dict[str, Any]] = None) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get((name, _labelset(labels)))

    def child(self, labels: Optional[Dict[str, Any]] = None):
        return self if not labels else _ChildView(self, labels)

    def series(self, prefix: str = "") -> List[Tuple[str, LabelSet, Histogram]]:
        with self._lock:
            return sorted(
                ((n, ls, h) for (n, ls), h in self._hists.items()
                 if n.startswith(prefix)),
                key=lambda t: (t[0], t[1]),
            )

    def snapshot(self, prefix: str = "") -> Dict[str, Dict[str, float]]:
        return {
            render_series(n, ls): h.snapshot() for n, ls, h in self.series(prefix)
        }

    def items(self) -> List[Tuple[str, Histogram]]:
        """Unlabeled-compatible view: (rendered name, Histogram) pairs."""
        return [(render_series(n, ls), h) for n, ls, h in self.series()]

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


histograms = Histograms()


class MetricsLogger:
    def __init__(
        self,
        project: Optional[str] = None,
        run_name: Optional[str] = None,
        config: Optional[dict] = None,
        enabled: bool = True,
        use_wandb: bool = False,
        log_file: Optional[str] = None,
        entity: Optional[str] = None,
    ):
        self.enabled = enabled
        self._wandb = None
        self._file = None
        if not enabled:
            return
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(project=project or "dalle_tpu", name=run_name,
                           entity=entity, config=config)
            except ImportError:
                print("wandb not installed; falling back to console logs", file=sys.stderr)
        if log_file:
            self._file = open(log_file, "a")
        if config:
            self.log_text(f"config: {json.dumps(config, default=str)}")

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        if not self.enabled:
            return
        if self._wandb is not None:
            self._wandb.log(metrics, step=step)
        line = " ".join(
            f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in metrics.items()
        )
        prefix = f"step {step}: " if step is not None else ""
        print(prefix + line, flush=True)
        if self._file:
            self._file.write(json.dumps({"step": step, **metrics}, default=str) + "\n")
            self._file.flush()

    def log_text(self, text: str) -> None:
        if self.enabled:
            print(text, flush=True)

    def log_counters(self, step: Optional[int] = None, prefix: str = "") -> None:
        """Emit the named fault counters (nonzero only) as metrics."""
        snap = {k: v for k, v in counters.snapshot(prefix).items() if v}
        if snap:
            self.log(snap, step=step)

    def log_images(self, name: str, images, step: Optional[int] = None, captions=None):
        """images: (b, h, w, 3) float in [0,1]; saved to wandb when active."""
        if not self.enabled or self._wandb is None:
            return
        imgs = [
            self._wandb.Image(
                (im * 255).clip(0, 255).astype("uint8"),
                caption=None if captions is None else captions[i],
            )
            for i, im in enumerate(images)
        ]
        self._wandb.log({name: imgs}, step=step)

    def log_histogram(self, name: str, values, step: Optional[int] = None):
        """Full-distribution histogram (the reference's codebook-collapse
        monitor, train_vae.py:252-262 logs wandb.Histogram(codes)); console
        falls back to a compact quantile summary."""
        if not self.enabled:
            return
        import numpy as np

        flat = np.asarray(values).reshape(-1)
        if self._wandb is not None:
            self._wandb.log({name: self._wandb.Histogram(flat)}, step=step)
        qs = np.percentile(flat, [0, 25, 50, 75, 100])
        self.log_text(
            f"step {step}: {name} histogram n={flat.size} "
            f"min/q25/med/q75/max={'/'.join(f'{q:g}' for q in qs)} "
            f"unique={np.unique(flat).size}"
        )

    def log_artifact(
        self,
        name: str,
        path: str,
        type: str = "model",
        metadata: Optional[dict] = None,
    ):
        """Upload a file as a wandb artifact (the reference's per-epoch
        checkpoint upload, train_dalle.py:637-649 / train_vae.py:298-313);
        no-op without an active wandb run."""
        if not self.enabled or self._wandb is None:
            return
        artifact = self._wandb.Artifact(name, type=type, metadata=metadata or {})
        artifact.add_file(path)
        self._wandb.run.log_artifact(artifact)

    def finish(self):
        if self._wandb is not None:
            self._wandb.finish()
        if self._file:
            self._file.close()


class Throughput:
    """sample_per_sec over an N-step window (train_dalle.py:621-624).

    The window test counts STEPS, not samples: the old
    ``total_samples % (samples * window)`` check silently never fired
    once per-step sample counts varied (last-batch remainder, ragged
    serving batches) — the running total stops being a multiple of the
    current step's ``samples * window`` and the rate is never emitted
    again. Samples are summed separately so the reported rate is exact
    for ragged windows too."""

    def __init__(self, window: int = 10):
        assert window > 0
        self.window = window
        self._t0 = time.perf_counter()
        self._steps = 0
        self._samples = 0

    def update(self, samples: int) -> Optional[float]:
        """Add one step's samples; returns samples/sec once per window."""
        self._steps += 1
        self._samples += samples
        if self._steps % self.window == 0:
            now = time.perf_counter()
            rate = self._samples / (now - self._t0)
            self._t0 = now
            self._samples = 0
            return rate
        return None


def mfu(flops_per_step: float, step_time_s: float, peak_flops: float) -> float:
    return flops_per_step / step_time_s / peak_flops
