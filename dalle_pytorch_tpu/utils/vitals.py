"""Engine vitals: sliding-window reductions over the existing metrics.

The cumulative Prometheus series (utils/metrics.py) answer "since boot";
an adaptive control loop needs "over the last few dozen iterations" —
the spec accept-rate RIGHT NOW, the decode-iteration gap RIGHT NOW. This
module computes those windows host-side, strictly as a READER of numbers
the engine already produces: the engine pushes one plain-number sample
set per iteration (``observe_iteration``), and ``publish`` reduces the
live windows into the ``serve.vitals.*`` gauges plus a snapshot dict the
controller (serving/control.py) consumes. Nothing here resets or mutates
the cumulative series — windowing is subtraction over ring samples
(metrics.GaugeRing) and checkpoint deltas (Histogram.snapshot_delta),
never a producer-side reset.

Host-only by lint contract (DTL021, tools/lint/config.py): no jax
anywhere in this module. Device facts enter as plain floats — the COST
LEDGER is charged by the ENGINE (the layer where jax is allowed) with
each serving jit's ``compiled.cost_analysis()`` FLOPs/bytes, once per
signature; this module only divides those numbers by wall time to keep
the per-iteration roofline fraction a live gauge instead of a bench
artifact (docs/DESIGN.md §8.6).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .metrics import GaugeRing

# device peaks for the live roofline gauge, keyed by jax device_kind —
# mirrors bench.py's PEAK_FLOPS/PEAK_HBM_BPS tables (bf16 matmul peak,
# HBM stream peak). Unknown kinds (CPU tiers) get None: the gauge reads
# 0.0 rather than inventing a CPU roofline.
DEVICE_PEAKS: Dict[str, Dict[str, float]] = {
    "TPU v4": {"flops": 275e12, "bytes_ps": 1.2e12},
    "TPU v5 lite": {"flops": 197e12, "bytes_ps": 0.82e12},
    "TPU v5e": {"flops": 197e12, "bytes_ps": 0.82e12},
    "TPU v5p": {"flops": 459e12, "bytes_ps": 2.77e12},
}


def peaks_for(device_kind: Optional[str]) -> Optional[Dict[str, float]]:
    """Peak FLOPs/s and HBM bytes/s for a device kind, or None when the
    kind has no table entry (roofline gauge stays 0)."""
    if device_kind is None:
        return None
    return DEVICE_PEAKS.get(device_kind)


class CostLedger:
    """Once-per-signature cost entries for the serving jits.

    The engine charges each jit name exactly once with the FLOPs and
    bytes its compiled executable reports (``cost_analysis()``); repeat
    charges are ignored so a steady-state iteration pays one dict probe.
    Entries are plain floats — the ledger is importable (and testable)
    anywhere the host-only observability layer is.
    """

    _GUARDED_BY = {"_lock": ("_entries",)}

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, float]] = {}

    def charge(self, name: str, flops: float, bytes_accessed: float) -> bool:
        """Record ``name``'s per-dispatch cost; False if already charged
        (the once-per-signature contract — first capture wins)."""
        with self._lock:
            if name in self._entries:
                return False
            self._entries[name] = {
                "flops": float(flops),
                "bytes_accessed": float(bytes_accessed),
            }
            return True

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def entry(self, name: str) -> Optional[Dict[str, float]]:
        with self._lock:
            e = self._entries.get(name)
            return dict(e) if e is not None else None

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def roofline_frac(self, name: str, dt_s: float,
                      peaks: Optional[Dict[str, float]]) -> float:
        """Fraction of the binding roof one dispatch of ``name`` achieved
        over ``dt_s`` wall seconds: max(FLOPs/s / peak_flops, bytes/s /
        peak_bytes). 0.0 when the name is uncharged, the peaks are
        unknown, or the window is degenerate (FakeClock dt=0)."""
        if peaks is None or dt_s <= 0.0:
            return 0.0
        e = self.entry(name)
        if e is None:
            return 0.0
        fracs = []
        if peaks.get("flops"):
            fracs.append(e["flops"] / dt_s / peaks["flops"])
        if peaks.get("bytes_ps"):
            fracs.append(e["bytes_accessed"] / dt_s / peaks["bytes_ps"])
        return max(fracs) if fracs else 0.0


def _window_delta(ring: GaugeRing) -> float:
    """last - first over a ring of CUMULATIVE samples — the windowed
    increment of a monotone counter series."""
    vals = ring.values()
    if len(vals) < 2:
        return 0.0
    return vals[-1] - vals[0]


class Vitals:
    """Sliding-window engine vitals, published as ``serve.vitals.*``.

    One ``observe_iteration`` per engine iteration (plain numbers only),
    one ``publish`` whenever the gauges should refresh. The window is
    measured in iterations (``window`` pushes per ring). Single-writer
    by design — the engine loop is the only producer — while the rings
    themselves are thread-safe for concurrent scrape-side readers.
    """

    def __init__(self, window: int = 32,
                 peaks: Optional[Dict[str, float]] = None):
        assert window >= 2, window
        self.window = window
        self.peaks = peaks
        self.ledger = CostLedger()
        # level series: windowed directly
        self._occupancy = GaugeRing(window)
        self._stage_lag = GaugeRing(window)
        self._gap = GaugeRing(window)
        # cumulative series: windowed as last-first ring deltas
        self._spec_drafted = GaugeRing(window)
        self._spec_accepted = GaugeRing(window)
        self._prefix_hits = GaugeRing(window)
        self._prefix_misses = GaugeRing(window)
        self._deadline_misses = GaugeRing(window)
        self._terminations = GaugeRing(window)
        self._last_now: Optional[float] = None
        self._last_jit: Optional[str] = None
        self._last_dt = 0.0
        self.iterations = 0

    def observe_iteration(
        self, *, now: float, occupancy: float, stage_queued: float,
        spec_drafted: float, spec_accepted: float,
        prefix_hits: float, prefix_misses: float,
        deadline_misses: float, terminations: float,
        jit_name: Optional[str] = None,
    ) -> None:
        """Push one iteration's sample set. All counter-style arguments
        are CUMULATIVE (lifetime) values; the vitals layer windows them."""
        if self._last_now is not None:
            self._last_dt = max(0.0, now - self._last_now)
            self._gap.push(self._last_dt)
        self._last_now = now
        self._last_jit = jit_name
        self._occupancy.push(occupancy)
        self._stage_lag.push(stage_queued)
        self._spec_drafted.push(spec_drafted)
        self._spec_accepted.push(spec_accepted)
        self._prefix_hits.push(prefix_hits)
        self._prefix_misses.push(prefix_misses)
        self._deadline_misses.push(deadline_misses)
        self._terminations.push(terminations)
        self.iterations += 1

    def snapshot(self) -> Dict[str, float]:
        """The windowed vitals the controller consumes — plain floats,
        every key present every time (a deterministic controller must
        never branch on key existence)."""
        drafted = _window_delta(self._spec_drafted)
        accepted = _window_delta(self._spec_accepted)
        hits = _window_delta(self._prefix_hits)
        misses = _window_delta(self._prefix_misses)
        dl = _window_delta(self._deadline_misses)
        terms = _window_delta(self._terminations)
        roofline = 0.0
        if self._last_jit is not None:
            roofline = self.ledger.roofline_frac(
                self._last_jit, self._last_dt, self.peaks
            )
        return {
            "iterations": float(self.iterations),
            "spec_accept_rate": accepted / drafted if drafted > 0 else 0.0,
            "spec_drafted": drafted,
            "prefix_hit_frac": (
                hits / (hits + misses) if hits + misses > 0 else 0.0
            ),
            "decode_gap_s": self._gap.window()["max"],
            "stage_lag": self._stage_lag.window()["mean"],
            "deadline_miss_rate": dl / terms if terms > 0 else 0.0,
            "occupancy": self._occupancy.window()["mean"],
            "roofline_frac": roofline,
        }

    def publish(self, gauges) -> Dict[str, float]:
        """Reduce the live windows into the ``serve.vitals.*`` gauges
        (``gauges``: the engine's label-bound registry view) and return
        the same snapshot dict for the controller."""
        snap = self.snapshot()
        gauges.set("serve.vitals.spec_accept_rate", snap["spec_accept_rate"])
        gauges.set("serve.vitals.prefix_hit_frac", snap["prefix_hit_frac"])
        gauges.set("serve.vitals.decode_gap_s", snap["decode_gap_s"])
        gauges.set("serve.vitals.stage_lag", snap["stage_lag"])
        gauges.set(
            "serve.vitals.deadline_miss_rate", snap["deadline_miss_rate"]
        )
        gauges.set("serve.vitals.occupancy", snap["occupancy"])
        gauges.set("serve.vitals.roofline_frac", snap["roofline_frac"])
        return snap
