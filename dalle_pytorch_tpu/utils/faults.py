"""Deterministic fault-injection registry.

Every resilience behavior in this package (download retry, shard
quarantine, torn-checkpoint fallback, NaN step skip) is testable on CPU
because its failure is *injectable* here instead of requiring a real
flaky network or a real preempted host. A fault site is a named counter:
code at the site asks the registry whether to fail, the registry
decrements, and after the armed count is exhausted the site behaves
normally — exactly the shape of a transient production fault.

Arming is programmatic (``FAULTS.arm("download", 2)``) or env-driven for
CLI/subprocess runs::

    DALLE_TPU_FAULTS="download=2,shard_open=1,nan_at_step=5,ckpt_corrupt=1"

Sites in use:

===============  =============================================================
``download``     ``utils.download``: the fetch raises ``URLError`` N times
``shard_open``   ``data.webdata``: ``open_shard`` raises ``OSError`` N times
``shard_read``   ``data.webdata``: a ``TarError`` is raised mid-shard N times
``ckpt_corrupt`` ``utils.checkpoint``: one payload file of the just-committed
                 step dir is corrupted after the manifest is written
``nan_at_step``  ``parallel.step`` via the trainer: the loss is forced to NaN
                 at global step K (value-style site: the armed count IS K)
``page_exhaust`` ``serving.engine``: a decode-time page allocation fails N
                 times even though the pool has free pages — forces the
                 preempt-and-requeue path without needing real pressure
``prefill_fail`` ``serving.engine``: the prefill pass raises a transient
                 ``RuntimeError`` N times (the request is requeued and
                 retried up to the engine's attempt budget)
``decode_stall`` ``serving.engine``: one decode iteration stalls — the
                 engine clock jumps by ``stall_penalty_s``, pushing
                 in-flight requests toward their deadlines
``request_cancel`` ``serving.engine``: the youngest running request is
                 cancelled mid-decode (models a client disconnect)
``telemetry_sink_fail`` ``utils.telemetry``: the flight-recorder drain's
                 write raises ``OSError`` N times — pins that telemetry
                 I/O failures stay counted and contained (fail open),
                 never propagating into the train/serve loop
``replica_crash`` ``serving.router``: the busiest live replica dies
                 abruptly — its engine is abandoned (unharvested results
                 lost, like a dead host's), its in-flight requests are
                 requeued to siblings, where (seed, position) sampling
                 replays them bit-identically
``replica_stall`` ``serving.router``: the busiest live replica skips one
                 scheduling step per armed count (a hung device
                 dispatch); sustained past ``stall_timeout_s`` the
                 heartbeat declares it dead and fails its work over
``health_flap``  ``serving.router``: the health check spuriously trips
                 the circuit breaker on a healthy replica (flapping
                 probe) — pins that breaker backoff prevents admission
                 livelock under repeated flaps
``prefix_hash_collide`` ``serving.prefix_cache``: a probe lookup returns
                 a FORGED chain node (a hash collision) — the mandatory
                 token-id verification must reject it and the engine
                 fall back to cold prefill, never serving another
                 prompt's K/V
``prefix_publish_fail`` ``serving.engine``: publishing a completed
                 request's prompt pages into the prefix index fails —
                 fail-open by contract: the request still completes
                 normally and its pages stay private (freed, unindexed)
``spec_verify_abort`` ``serving.engine``: the speculative drafter fails
                 for one iteration — the engine degrades that iteration
                 to PLAIN decode (verify width 1, no drafts consumed)
                 through the same jit signature; output is bit-identical
                 by construction (exact acceptance makes a width-1
                 verify row a plain decode row) and the fallback is
                 counted (``serve.spec.fallbacks``)
``replica_respawn_fail`` ``serving.router``: a scheduled replica
                 respawn attempt fails (the rebuilt engine never comes
                 up) — the respawn state machine must back off and
                 retry, escalating to permanently DEAD only after
                 ``max_respawns`` failures
``journal_torn`` ``serving.journal``: the request journal's tail record
                 is truncated mid-append (a crash tore the last write) —
                 the loader must DROP the torn tail, count it
                 (``serve.journal.torn``), and replay the intact prefix
``snapshot_corrupt`` ``serving.engine``: a prefix-cache snapshot fails
                 its mandatory verify-on-load (a token block no longer
                 matches its chain digest) — the whole snapshot is
                 REJECTED (``serve.snapshot.rejected``) and the engine
                 falls back to a cold index, never mapping corrupt K/V
``vae_decode_fail`` ``serving.postdecode``: one VAE_DECODE stage
                 dispatch fails transiently — the batch retries with
                 backoff; exhaustion completes the requests typed
                 ``completed_tokens_only`` (graceful degradation,
                 DESIGN.md §8.5), never stalled or dropped
``rerank_fail``  ``serving.postdecode``: one CLIP_RERANK stage dispatch
                 fails transiently — retries with backoff; exhaustion
                 completes the requests typed ``completed_unranked``
                 (the decoded image survives, only the score is shed)
``stage_timeout`` ``serving.postdecode``: one stage dispatch exceeds its
                 per-dispatch time budget — same retry-then-degrade
                 path as a stage failure, counted separately
                 (``serve.stage.timeouts``)
``control_stall`` ``serving.control``: one controller evaluation raises
                 (a stuck/buggy control loop) — the engine degrades that
                 evaluation to the STATIC config defaults (every
                 effective knob reset), typed and counted
                 (``serve.control.stalls``); decode progress never
                 depends on the controller being alive
===============  =============================================================

Injection must be impossible to leave on by accident: the registry is
inert unless armed, ``tests/conftest.py`` asserts the env var is unset,
and every consumed fault is tallied in ``fired`` for assertions.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

ENV_VAR = "DALLE_TPU_FAULTS"

# sites whose armed number is a parameter (e.g. a step index), not a count
# of failures to consume
_VALUE_SITES = frozenset({"nan_at_step"})

# every site referenced by production code; the env-spec parser rejects
# anything else so a typo'd site name fails the run instead of silently
# injecting nothing (programmatic ``arm`` stays open for test-local sites)
KNOWN_SITES = frozenset({
    "download", "shard_open", "shard_read", "ckpt_corrupt", "nan_at_step",
    "page_exhaust", "prefill_fail", "decode_stall", "request_cancel",
    "telemetry_sink_fail",
    "replica_crash", "replica_stall", "health_flap",
    "prefix_hash_collide", "prefix_publish_fail",
    "spec_verify_abort",
    "replica_respawn_fail", "journal_torn", "snapshot_corrupt",
    "vae_decode_fail", "rerank_fail", "stage_timeout",
    "control_stall",
})


def _parse_spec(spec: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad {ENV_VAR} entry {part!r}: want site=count"
            )
        site, _, count = part.partition("=")
        site = site.strip()
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r} in {ENV_VAR} "
                f"(known: {sorted(KNOWN_SITES)})"
            )
        out[site] = int(count)
    return out


class FaultRegistry:
    """Named, counted injection points. Thread-safe (loaders prefetch in
    background threads)."""

    def __init__(self, spec: Optional[str] = None):
        self._lock = threading.Lock()
        self._armed: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        if spec:
            self.configure(spec)

    # ----------------------------------------------------------- arming
    def configure(self, spec: str) -> None:
        """Arm from a ``site=count,...`` spec (the env-var format)."""
        for site, count in _parse_spec(spec).items():
            self.arm(site, count)

    def arm(self, site: str, count: int = 1) -> None:
        with self._lock:
            self._armed[site] = count

    def reset(self) -> None:
        with self._lock:
            self._armed.clear()
            self.fired.clear()

    # ---------------------------------------------------------- querying
    def active(self) -> bool:
        with self._lock:
            return any(v > 0 or k in _VALUE_SITES for k, v in self._armed.items())

    def value(self, site: str) -> Optional[int]:
        """Parameter-style read (e.g. ``nan_at_step`` -> the step index);
        does not consume. None when the site is unarmed."""
        with self._lock:
            return self._armed.get(site)

    def take(self, site: str) -> bool:
        """Consume one armed failure at ``site``. True exactly ``count``
        times after ``arm(site, count)``, then False forever."""
        with self._lock:
            remaining = self._armed.get(site, 0)
            if site in _VALUE_SITES or remaining <= 0:
                return False
            self._armed[site] = remaining - 1
            self.fired[site] = self.fired.get(site, 0) + 1
            return True

    def maybe_raise(self, site: str, exc: Exception) -> None:
        """Raise ``exc`` if a failure is armed at ``site`` (consuming it)."""
        if self.take(site):
            raise exc


# process-wide registry; env spec is read once at import so CLI subprocesses
# (the e2e tests drive real CLIs) inherit armed faults through the
# environment without any plumbing
FAULTS = FaultRegistry(os.environ.get(ENV_VAR))
