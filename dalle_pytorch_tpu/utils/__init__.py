from .checkpoint import (
    latest_verified_step,
    load_checkpoint,
    load_sharded_checkpoint,
    save_checkpoint,
    save_sharded_checkpoint,
    verify_step_dir,
)
from .download import CACHE_DIR, download
from .faults import FAULTS, FaultRegistry
from .metrics import (
    Counters,
    Gauges,
    Histogram,
    Histograms,
    MetricsLogger,
    Throughput,
    counters,
    gauges,
    histograms,
    mfu,
)
from .quantize import (
    prepare_for_serving,
    quantize_dalle,
    quantize_kernel,
    quantize_params,
)
from .resilience import PreemptionHandler, RetryPolicy, retry
from .schedules import (
    ConstantLR,
    ExponentialDecay,
    ReduceLROnPlateau,
    gumbel_temperature,
)
from .telemetry import TELEMETRY, Telemetry, validate_flight_file
from . import telemetry_names

__all__ = [
    "CACHE_DIR",
    "ConstantLR",
    "Counters",
    "ExponentialDecay",
    "FAULTS",
    "FaultRegistry",
    "Gauges",
    "Histogram",
    "Histograms",
    "MetricsLogger",
    "TELEMETRY",
    "Telemetry",
    "telemetry_names",
    "PreemptionHandler",
    "ReduceLROnPlateau",
    "RetryPolicy",
    "Throughput",
    "counters",
    "download",
    "gauges",
    "gumbel_temperature",
    "histograms",
    "latest_verified_step",
    "load_checkpoint",
    "load_sharded_checkpoint",
    "mfu",
    "prepare_for_serving",
    "quantize_dalle",
    "quantize_kernel",
    "quantize_params",
    "retry",
    "save_checkpoint",
    "save_sharded_checkpoint",
    "validate_flight_file",
    "verify_step_dir",
]
