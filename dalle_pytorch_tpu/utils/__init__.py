from .checkpoint import (
    load_checkpoint,
    load_sharded_checkpoint,
    save_checkpoint,
    save_sharded_checkpoint,
)
from .download import CACHE_DIR, download
from .metrics import MetricsLogger, Throughput, mfu
from .quantize import (
    prepare_for_serving,
    quantize_dalle,
    quantize_kernel,
    quantize_params,
)
from .schedules import (
    ConstantLR,
    ExponentialDecay,
    ReduceLROnPlateau,
    gumbel_temperature,
)

__all__ = [
    "CACHE_DIR",
    "ConstantLR",
    "ExponentialDecay",
    "MetricsLogger",
    "ReduceLROnPlateau",
    "Throughput",
    "download",
    "gumbel_temperature",
    "load_checkpoint",
    "load_sharded_checkpoint",
    "mfu",
    "prepare_for_serving",
    "quantize_dalle",
    "quantize_kernel",
    "quantize_params",
    "save_checkpoint",
    "save_sharded_checkpoint",
]
