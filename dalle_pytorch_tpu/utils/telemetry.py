"""Unified telemetry: spans, events, flight recorder, and /metrics.

The observability layer every hot path reports through (docs/DESIGN.md
§9). Three pieces, one module:

**Spans and events.** ``span("serve.prefill", request_id=...)`` is a
context manager timing one host-side phase; ``begin``/``end`` are the
non-lexical form for spans that straddle loop iterations (a serving
request's whole lifecycle, a train step from dispatch to its verdict);
``event(...)`` is a point-in-time record. Every record is a flat dict —
``{"ts", "ph" ("B"|"E"|"I"), "name", "id", "parent", **attrs}`` — on a
monotonic clock. The clock is injectable and duck-types the serving
``Clock`` protocol (``.now() -> float``; ``serving/types.py``), so
``FakeClock``-driven tests pin span timing deterministically. Span
durations are auto-observed into a ``<name>_s`` histogram
(``utils.metrics.histograms``), which is how request latency, queue
wait, step time, and data wait become first-class percentiles instead
of ad-hoc sorts in bench code.

**Flight recorder.** Records land in a bounded in-memory ring buffer;
when a flight directory is configured, a full ring DRAINS to a JSONL
file (rotation) instead of dropping, and drains also fire from the
``PreemptionHandler`` signal callback and an atexit hook — so a SIGTERM
or NaN-abort leaves a structured record of the run's last seconds, with
any still-open ``"B"`` records showing exactly what was in flight.
Without a flight dir the ring drops oldest (counted). Telemetry is
observability, not control: every sink failure FAILS OPEN — counted
under ``telemetry.sink_errors`` (injectable via the
``telemetry_sink_fail`` fault site), never raised into train/serve.

**Exposition.** ``dump()`` renders counters, gauges, and histograms as
Prometheus-style text; ``serve_metrics(port)`` serves it at
``GET /metrics`` from a stdlib ``http.server`` daemon thread bound to
127.0.0.1 only (no auth — localhost scrape or port-forward; off by
default). Root-rank-guard the same way ``MetricsLogger`` is: only the
root worker passes ``enabled=True``.

Disabled (the default) is a TRUE no-op: no threads, no files, no
records — ``span()`` yields immediately. Enable programmatically
(``TELEMETRY.configure(enabled=True, ...)``) or by environment for CLI
subprocesses, mirroring ``DALLE_TPU_FAULTS``::

    DALLE_TPU_TELEMETRY=1
    DALLE_TPU_TELEMETRY_DIR=/tmp/flight     # optional: flight recorder
    DALLE_TPU_TELEMETRY_PORT=9100           # optional: /metrics server

This module is deliberately host-side only — it must never import jax
or touch device values (a per-token device sync would be a measurement
that destroys what it measures); callers pass plain Python numbers.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .faults import FAULTS
from .metrics import counters, gauges, histograms

ENV_ENABLE = "DALLE_TPU_TELEMETRY"
ENV_DIR = "DALLE_TPU_TELEMETRY_DIR"
ENV_PORT = "DALLE_TPU_TELEMETRY_PORT"


class _MonotonicClock:
    """Default time source; same protocol as ``serving.types.Clock``
    (duck-typed here so telemetry never imports the serving package)."""

    def now(self) -> float:
        import time

        return time.monotonic()


class Telemetry:
    """See module docstring. One process-wide instance (``TELEMETRY``)
    is the normal entry point; tests build private ones.

    The ring state below is written from serve/train threads, loader
    threads, AND re-entrantly from signal handlers; ``_GUARDED_BY`` is
    the machine-checked contract for which fields the RLock guards
    (tools/lint.py DTL051, docs/DESIGN.md §11)."""

    _GUARDED_BY = {"_lock": ("_buf", "_open", "_next_id", "_flight_path")}

    def __init__(self, clock=None, ring_size: int = 4096):
        self._lock = threading.RLock()  # reentrant: drain can fire from a
        # signal handler interrupting a thread that already holds the lock
        self.clock = clock or _MonotonicClock()
        self.enabled = False
        self.ring_size = int(ring_size)
        self._buf: deque = deque()
        self._open: Dict[int, Tuple[str, float]] = {}  # sid -> (name, t0)
        self._tls = threading.local()  # per-thread span stack (nesting)
        self._next_id = 1
        self.dropped = 0
        self.sink_errors = 0
        self.flight_dir: Optional[str] = None
        self.flight_max_bytes = 16 << 20
        self._flight_path: Optional[str] = None
        self._server = None
        self._server_thread = None
        self._atexit_registered = False

    # ------------------------------------------------------------- config

    def configure(
        self,
        enabled: Optional[bool] = None,
        ring_size: Optional[int] = None,
        flight_dir: Optional[str] = None,
        flight_max_bytes: Optional[int] = None,
        metrics_port: Optional[int] = None,
        clock=None,
    ) -> "Telemetry":
        """Reconfigure in place; returns self. ``enabled=False`` tears
        everything down (server thread stopped, atexit unregistered) so a
        disabled config is a true no-op even after a previous enable."""
        with self._lock:
            if clock is not None:
                self.clock = clock
            if ring_size is not None:
                assert ring_size > 0
                self.ring_size = int(ring_size)
            if flight_dir is not None:
                self.flight_dir = flight_dir or None
                self._flight_path = None
            if flight_max_bytes is not None:
                self.flight_max_bytes = int(flight_max_bytes)
            if enabled is not None:
                self.enabled = bool(enabled)
            if not self.enabled:
                self._stop_server()
                self._unregister_atexit()
                return self
            if self.flight_dir and not self._atexit_registered:
                atexit.register(self._atexit_drain)
                self._atexit_registered = True
            if metrics_port is not None:
                self.serve_metrics(metrics_port)
        return self

    def reset(self) -> None:
        """Back to the pristine disabled state (test hermeticity)."""
        with self._lock:
            self.configure(enabled=False)
            self._buf.clear()
            self._open.clear()
            self.dropped = 0
            self.sink_errors = 0
            self.flight_dir = None
            self._flight_path = None
            self.clock = _MonotonicClock()
            self._tls = threading.local()

    # -------------------------------------------------------------- spans

    def _stack(self) -> List[int]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def begin(self, name: str, parent: Optional[int] = None,
              **attrs: Any) -> Optional[int]:
        """Open a non-lexical span; returns its id (None when disabled —
        ``end(None)`` is a no-op, so call sites need no guards). The
        parent defaults to the calling thread's innermost ``span()``."""
        if not self.enabled:
            return None
        t = self.clock.now()
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            self._open[sid] = (name, t)
        if parent is None:
            st = self._stack()
            parent = st[-1] if st else None
        self._record({"ts": t, "ph": "B", "name": name, "id": sid,
                      "parent": parent, **attrs})
        return sid

    def end(self, span_id: Optional[int], **attrs: Any) -> None:
        """Close a span opened with ``begin``; observes its duration into
        the ``<name>_s`` histogram."""
        if span_id is None or not self.enabled:
            return
        with self._lock:
            name, t0 = self._open.pop(span_id, (None, None))
        t = self.clock.now()
        rec = {"ts": t, "ph": "E", "id": span_id, **attrs}
        if name is not None:
            rec["name"] = name
            rec["dur_s"] = t - t0
            histograms.observe(f"{name}_s", t - t0)
        self._record(rec)

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Optional[int]]:
        """Lexical span: times the with-block, nests via a per-thread
        stack (children record this span as ``parent``)."""
        if not self.enabled:
            yield None
            return
        sid = self.begin(name, **attrs)
        st = self._stack()
        st.append(sid)
        try:
            yield sid
        finally:
            if st and st[-1] == sid:
                st.pop()
            self.end(sid)

    def event(self, name: str, **attrs: Any) -> None:
        """Point-in-time record (``ph: "I"``)."""
        if not self.enabled:
            return
        st = self._stack()
        parent = attrs.pop("parent", st[-1] if st else None)
        self._record({"ts": self.clock.now(), "ph": "I", "name": name,
                      "parent": parent, **attrs})

    # ------------------------------------------------------- ring + drain

    def _record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._buf) >= self.ring_size:
                if self.flight_dir:
                    self._drain_locked("ring_full")  # rotation
                else:
                    self._buf.popleft()  # oldest dropped, counted
                    self.dropped += 1
                    counters.inc("telemetry.dropped")
            self._buf.append(rec)

    def drain(self, reason: str = "explicit") -> Optional[str]:
        """Flush the ring to the flight-recorder file. Returns the file
        path (None when there is nothing to write or no dir configured).
        NEVER raises — telemetry fails open (docs/DESIGN.md §9)."""
        if not self.enabled:
            return None
        with self._lock:
            return self._drain_locked(reason)

    def _drain_locked(self, reason: str) -> Optional[str]:
        if not self.flight_dir or not self._buf:
            return None
        records = list(self._buf)
        self._buf.clear()  # fail open: a failed write drops, never blocks
        try:
            FAULTS.maybe_raise(
                "telemetry_sink_fail", OSError("injected telemetry_sink_fail")
            )
            path = self._flight_file_locked()
            lines = [json.dumps(rec, default=str) for rec in records]
            lines.append(json.dumps(
                {"ts": self.clock.now(), "ph": "I",
                 "name": "telemetry.drain", "n": len(records),
                 "reason": reason, "dropped": self.dropped}
            ))
            data = ("\n".join(lines) + "\n").encode()
            # ONE unbuffered append write, not a buffered loop: a SIGTERM
            # drain re-entering through the RLock mid-loop would otherwise
            # interleave its complete lines between a buffered writer's
            # partial flushes and tear a JSON line — the nested drain now
            # lands entirely before or after this block
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                while data:
                    data = data[os.write(fd, data):]
            finally:
                os.close(fd)
            return path
        except Exception as e:
            self.sink_errors += 1
            counters.inc("telemetry.sink_errors")
            try:
                import sys

                print(f"telemetry drain failed (open): {type(e).__name__}: {e}",
                      file=sys.stderr)
            except Exception:
                pass
            return None

    def _flight_file_locked(self) -> str:
        """Per-PID JSONL path; rotates (one generation, ``.1``) past
        ``flight_max_bytes`` so a long-lived server bounds its disk use.
        ``_locked``: only called under ``_lock`` (from the drain)."""
        if self._flight_path is None:
            os.makedirs(self.flight_dir, exist_ok=True)
            self._flight_path = os.path.join(
                self.flight_dir, f"flight-{os.getpid()}.jsonl"
            )
        p = self._flight_path
        try:
            if os.path.getsize(p) > self.flight_max_bytes:
                os.replace(p, p + ".1")
        except OSError:
            pass  # no file yet
        return p

    def _atexit_drain(self) -> None:
        try:
            self.drain("atexit")
        except Exception:
            pass  # fail open, even at interpreter teardown

    # --------------------------------------------------------- exposition

    @staticmethod
    def _prom_name(name: str) -> str:
        out = []
        for ch in name:
            out.append(ch if ch.isalnum() or ch == "_" else "_")
        s = "".join(out)
        return ("_" + s) if s and s[0].isdigit() else (s or "_")

    @staticmethod
    def _prom_labels(labelset, extra: str = "") -> str:
        """Render a metrics ``LabelSet`` (plus an optional pre-rendered
        pair like ``le="..."``) as a ``{...}`` sample suffix."""
        parts = [f'{k}="{v}"' for k, v in labelset]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def dump(self) -> str:
        """Prometheus-style text exposition of every counter, gauge, and
        histogram in ``utils.metrics`` plus the telemetry self-metrics.
        Labeled series (``serve.occupancy{replica="1"}`` — per-replica
        serving metrics) render as proper label'd samples sharing one
        ``# TYPE`` line per metric name."""
        lines: List[str] = []
        typed: set = set()

        def type_line(n: str, kind: str) -> None:
            if n not in typed:
                typed.add(n)
                lines.append(f"# TYPE {n} {kind}")

        for name, labelset, v in counters.series():
            n = self._prom_name(name)
            type_line(n, "counter")
            lines.append(f"{n}{self._prom_labels(labelset)} {v}")
        for name, labelset, v in gauges.series():
            n = self._prom_name(name)
            type_line(n, "gauge")
            lines.append(f"{n}{self._prom_labels(labelset)} {v:g}")
        for name, labelset, hist in histograms.series():
            n = self._prom_name(name)
            type_line(n, "histogram")
            # one atomic snapshot per histogram: buckets/_sum/_count/
            # quantiles must agree within a scrape (a concurrent
            # observe() between separate locked reads would render a
            # _count above the +Inf bucket)
            exp = hist.exposition()
            for ub, cum in exp["buckets"]:
                le = "+Inf" if ub == float("inf") else f"{ub:.6g}"
                suffix = self._prom_labels(labelset, f'le="{le}"')
                lines.append(f"{n}_bucket{suffix} {cum}")
            lines.append(
                f"{n}_sum{self._prom_labels(labelset)} {exp['sum']:.9g}"
            )
            lines.append(
                f"{n}_count{self._prom_labels(labelset)} {exp['count']}"
            )
            for q, label in ((50, "0.5"), (95, "0.95"), (99, "0.99")):
                suffix = self._prom_labels(labelset, f'quantile="{label}"')
                lines.append(f"{n}{suffix} {exp['quantiles'][q]:.9g}")
        lines.append("# TYPE telemetry_ring_dropped counter")
        lines.append(f"telemetry_ring_dropped {self.dropped}")
        lines.append("# TYPE telemetry_sink_errors counter")
        lines.append(f"telemetry_sink_errors {self.sink_errors}")
        return "\n".join(lines) + "\n"

    def serve_metrics(self, port: int) -> Optional[int]:
        """Start the /metrics daemon thread on 127.0.0.1:``port`` (0 picks
        a free port); returns the bound port. Idempotent; no-op when
        disabled. Localhost-only by design — see the security note in
        docs/DESIGN.md §9."""
        if not self.enabled:
            return None
        with self._lock:
            if self._server is not None:
                return self._server.server_address[1]
            from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

            telemetry = self

            class Handler(BaseHTTPRequestHandler):
                def do_GET(self):  # noqa: N802 (stdlib API name)
                    if self.path.rstrip("/") not in ("", "/metrics".rstrip("/")):
                        self.send_error(404)
                        return
                    body = telemetry.dump().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def log_message(self, *a):  # silence per-request stderr spam
                    pass

            try:
                self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
            except OSError as e:
                self.sink_errors += 1
                counters.inc("telemetry.sink_errors")
                import sys

                print(f"telemetry /metrics bind failed (open): {e}",
                      file=sys.stderr)
                return None
            self._server_thread = threading.Thread(
                target=self._server.serve_forever,
                name="telemetry-metrics",
                daemon=True,
            )
            self._server_thread.start()
            return self._server.server_address[1]

    def _stop_server(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if self._server_thread is not None:
                self._server_thread.join(timeout=5)
            self._server = None
            self._server_thread = None

    def _unregister_atexit(self) -> None:
        if self._atexit_registered:
            atexit.unregister(self._atexit_drain)
            self._atexit_registered = False


# process-wide instance; env spec read once at import so CLI subprocesses
# (smoke gates, e2e tests) inherit an enabled recorder through the
# environment with zero plumbing — the FAULTS pattern
TELEMETRY = Telemetry()
if os.environ.get(ENV_ENABLE, "") not in ("", "0", "false"):
    _port: Optional[int] = None
    if os.environ.get(ENV_PORT):
        try:
            _port = int(os.environ[ENV_PORT])
        except ValueError:
            # fail open, like every other telemetry error: a typo'd port
            # must not turn package import into a crash
            import sys as _sys

            print(
                f"ignoring non-integer {ENV_PORT}="
                f"{os.environ[ENV_PORT]!r} (telemetry fails open)",
                file=_sys.stderr,
            )
    TELEMETRY.configure(
        enabled=True,
        flight_dir=os.environ.get(ENV_DIR),
        metrics_port=_port,
    )


def validate_flight_file(path: str) -> Dict[str, Any]:
    """Parse + structurally validate a flight-recorder JSONL file: every
    line must parse, every ``E`` must follow a matching ``B`` (same id).
    A rotated previous generation (``<path>.1``) is stitched in first, so
    a span whose B/E pair straddles a size-cap rotation still balances;
    an E whose B was rotated beyond the kept generation is counted under
    ``orphan_ends`` (only possible past TWO rotations), not an error.
    Returns a summary dict with ``records``, ``spans`` (closed),
    ``unclosed`` (ids still open — legitimate in a crash/preemption
    capture: they ARE the postmortem), ``orphan_ends``, and ``by_name``
    counts. Raises ValueError on structural corruption. Shared by
    tools/telemetry_smoke.py and the tests."""
    prev = path + ".1"
    streams = [prev, path] if os.path.exists(prev) else [path]
    rotated = len(streams) > 1
    open_spans: Dict[int, Dict[str, Any]] = {}
    closed = 0
    records = 0
    orphan_ends = 0
    by_name: Dict[str, int] = {}
    for fpath in streams:
        with open(fpath) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    raise ValueError(f"{fpath}:{lineno}: bad JSON: {e}")
                records += 1
                ph = rec.get("ph")
                ts = rec.get("ts")
                if ph not in ("B", "E", "I") or not isinstance(ts, (int, float)):
                    raise ValueError(f"{fpath}:{lineno}: malformed record {rec}")
                if "name" in rec:
                    by_name[rec["name"]] = by_name.get(rec["name"], 0) + 1
                if ph == "B":
                    open_spans[rec["id"]] = rec
                elif ph == "E":
                    if rec["id"] in open_spans:
                        open_spans.pop(rec["id"])
                        closed += 1
                    elif rotated:
                        orphan_ends += 1  # its B fell off the .1 horizon
                    else:
                        raise ValueError(
                            f"{fpath}:{lineno}: E without B for id {rec['id']}"
                        )
    return {
        "records": records,
        "spans": closed,
        "unclosed": sorted(open_spans),
        "unclosed_records": list(open_spans.values()),
        "orphan_ends": orphan_ends,
        "by_name": by_name,
    }
