"""Per-module FLOPs breakdown from a compiled XLA program.

The analog of the reference's DeepSpeed flops-profiler table
(/root/reference/train_dalle.py:473-480 prints a module-depth breakdown of
FLOPs/latency): here the numbers come from the compiled HLO itself — every
``dot``/``convolution`` op's FLOPs are computed from its shapes and charged
to the flax module scope recorded in its ``op_name`` metadata (the jax name
stack, e.g. ``jit(train_step)/jvp(DALLE)/transformer/attn_3/...``), so the
table reflects what XLA actually compiled, not a hand model. Pallas kernels
appear as ``custom-call`` ops whose FLOPs XLA cannot see; they are charged
from the caller-supplied analytic estimate (the same CostEstimates the
kernels feed XLA's scheduler).

``jvp(...)`` scopes are forward ops, ``transpose(jvp(...))`` backward —
the table splits the two the way the reference's profiler splits
fwd/bwd latency.
"""

from __future__ import annotations

import re
from collections import defaultdict
from typing import Callable, Dict, Optional, Tuple

_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*\w+\[([\d,]*)\]")
_OPND_RE = re.compile(r"%([\w.\-]+)")
_META_RE = re.compile(r'op_name="([^"]*)"')


def _shape_of(defs: Dict[str, Tuple[int, ...]], name: str) -> Tuple[int, ...]:
    return defs.get(name, ())


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def parse_hlo_flops(
    hlo_text: str,
    custom_call_flops: Optional[Callable[[str], float]] = None,
) -> Dict[str, Dict[str, float]]:
    """HLO text -> {module_scope: {"fwd": flops, "bwd": flops}}.

    module_scope is the op_name path with the jit/jvp wrappers stripped,
    truncated to the first two user components (e.g. ``transformer/attn_3``,
    ``to_logits``). ``custom_call_flops(line)`` supplies accounting for
    opaque custom-calls — pallas kernels carry no op_name metadata in the
    compiled HLO, so the callback receives the whole line and returns
    (scope, "fwd" | "bwd", flops) or None to skip.
    """
    defs: Dict[str, Tuple[int, ...]] = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line)
        if m:
            name, dims = m.groups()
            defs[name] = tuple(int(d) for d in dims.split(",")) if dims else ()

    out: Dict[str, Dict[str, float]] = defaultdict(lambda: {"fwd": 0.0, "bwd": 0.0})
    unparsed_dots = 0

    for line in hlo_text.splitlines():
        line = line.strip()
        meta = _META_RE.search(line)
        op_name = meta.group(1) if meta else ""
        flops = 0.0

        if " dot(" in line or line.startswith("dot("):
            m = _DEF_RE.match(line)
            if not m:
                continue
            out_shape = defs[m.group(1)]
            # operands appear right after "dot("
            args = _OPND_RE.findall(line.split(" dot(", 1)[1])
            lhs_shape = _shape_of(defs, args[0]) if args else ()
            cdims = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", line)
            if cdims and not lhs_shape:
                # operand defined in a line form the regex didn't capture —
                # surface the gap rather than silently charging contracted=1
                unparsed_dots += 1
                continue
            contracted = _prod(
                lhs_shape[int(i)] for i in cdims.group(1).split(",") if i
            ) if (cdims and lhs_shape) else 1
            flops = 2.0 * _prod(out_shape) * contracted
        elif " convolution(" in line:
            m = _DEF_RE.match(line)
            if not m:
                continue
            out_shape = defs[m.group(1)]
            args = _OPND_RE.findall(line.split(" convolution(", 1)[1])
            rhs_shape = _shape_of(defs, args[1]) if len(args) > 1 else ()
            dnums = re.search(r"dim_labels=([\w.]+)_([\w.]+)->", line)
            if rhs_shape and dnums:
                rhs_labels = dnums.group(2)
                # rhs output-feature dim is labeled 'o' (kernel iOhw forms)
                o_idx = rhs_labels.index("o" if "o" in rhs_labels else "f")
                per_out = _prod(rhs_shape) // max(int(rhs_shape[o_idx]), 1)
                flops = 2.0 * _prod(out_shape) * per_out
                # lhs-dilated (transposed/grad) convs: the dilation factor of
                # the multiplications hits inserted zeros and is never
                # executed — XLA's cost model counts only real MACs, so
                # divide to match (flags transposed decoder convs otherwise
                # overcounted 4x at stride 2)
                dil = re.search(r"lhs_dilate=([\dx]+)", line)
                if dil:
                    flops /= _prod(int(d) for d in dil.group(1).split("x"))
        elif "custom-call" in line and custom_call_flops is not None:
            acc = custom_call_flops(line)
            if acc:
                scope, kind, cc_flops = acc
                out[scope][kind] += float(cc_flops)
            continue
        if flops <= 0:
            continue

        is_bwd = "transpose(" in op_name
        scope = scope_of(op_name)
        out[scope]["bwd" if is_bwd else "fwd"] += flops
    if unparsed_dots:
        import warnings

        warnings.warn(
            f"hlo_breakdown: {unparsed_dots} dot op(s) had unresolvable "
            "operand shapes; their FLOPs are omitted from the table",
            stacklevel=2,
        )
    return dict(out)


def scope_of(op_name: str) -> str:
    """op_name metadata -> short module scope: strip jit/jvp/transpose/
    named wrappers and keep the first two model components."""
    parts = [
        p for p in op_name.split("/")
        if p and not re.match(r"^(jit|jvp|transpose|vmap|while|body|cond|remat|checkpoint|custom[-_]vjp|named)\b", p)
        and not p.startswith("broadcast_in_dim")
    ]
    # drop flax's anonymous fn wrappers and trailing primitive names
    parts = [p for p in parts if p not in ("fn", "model")]
    if not parts:
        return "(other)"
    # first component that looks like a module, plus one level below it
    keep = parts[:2]
    # a trailing primitive (dot_general etc.) is not a module level
    if len(keep) == 2 and re.match(r"^(dot_general|conv|add|mul|custom)", keep[1]):
        keep = keep[:1]
    return "/".join(keep)


def format_table(
    groups: Dict[str, Dict[str, float]],
    step_time_s: Optional[float] = None,
    peak_flops: Optional[float] = None,
) -> str:
    """Render the per-module table (sorted by total FLOPs, descending).
    When step_time_s is given, a proportional-time estimate column is added
    (FLOPs share x measured step time — an estimate, not a measured
    per-module latency)."""
    total = sum(v["fwd"] + v["bwd"] for v in groups.values()) or 1.0
    rows = sorted(groups.items(), key=lambda kv: -(kv[1]["fwd"] + kv[1]["bwd"]))
    lines = []
    header = f"{'module':<28}{'fwd GFLOPs':>12}{'bwd GFLOPs':>12}{'total':>10}{'share':>8}"
    if step_time_s:
        header += f"{'~ms':>8}"
    lines.append(header)
    lines.append("-" * len(header))
    for name, v in rows:
        t = v["fwd"] + v["bwd"]
        line = (
            f"{name:<28}{v['fwd'] / 1e9:>12.2f}{v['bwd'] / 1e9:>12.2f}"
            f"{t / 1e9:>10.2f}{t / total:>8.1%}"
        )
        if step_time_s:
            line += f"{t / total * step_time_s * 1e3:>8.2f}"
        lines.append(line)
    lines.append("-" * len(header))
    foot = f"{'TOTAL':<28}{sum(v['fwd'] for v in groups.values()) / 1e9:>12.2f}" \
           f"{sum(v['bwd'] for v in groups.values()) / 1e9:>12.2f}{total / 1e9:>10.2f}{'100%':>8}"
    if step_time_s:
        foot += f"{step_time_s * 1e3:>8.2f}"
    lines.append(foot)
    if step_time_s and peak_flops:
        lines.append(
            f"step {step_time_s * 1e3:.2f} ms | {total / step_time_s / 1e12:.1f} TF/s "
            f"achieved | {total / step_time_s / peak_flops:.1%} of peak"
        )
    return "\n".join(lines)
