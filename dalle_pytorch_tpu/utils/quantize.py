"""Post-training weight-only int8 quantization for serving.

Autoregressive decode reads every transformer kernel from HBM once per
generated token — at the flagship config that is ~0.4 GB/token in bf16 and
is the dominant cost of single-chip generation (the reference has no
quantized serving path at all; its sampling re-runs full forwards in fp16
at best, dalle_pytorch.py:481-493). Converting the Dense kernels (per-
output-channel symmetric scales) and the token-embedding tables (per-row
scales) to int8 halves those bytes; activations, norms, biases and every
other parameter stay in full precision, and the matvecs/gathers widen
int8 -> bf16 in registers (see ops/layers.py:QuantDense / QuantEmbed).

``quantize_dalle`` maps a trained DALLE + params to its ``serve_quant``
twin: the target parameter tree comes from ``jax.eval_shape`` on the quant
model's init (no compute), and each leaf is either copied from the source
tree or quantized from the matching kernel. flax auto-names swap
``Dense_i`` -> ``QuantDense_i`` inside feed-forward blocks; explicitly
named projections (to_qkv / to_out / to_logits) keep their paths.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import traverse_util


def quantize_kernel(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(in, out) float kernel -> (int8 kernel, (out,) f32 scale), symmetric
    per-output-channel: q = round(w / s), s = max|w_col| / 127."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def quantize_embedding(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(vocab, dim) float table -> (int8 table, (vocab,) f32 scale),
    symmetric per-row (each gathered row dequantizes independently)."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


def _src_path(path: Tuple[str, ...]) -> Tuple[str, ...]:
    """Target (quant) tree path -> source tree path: un-rename the flax
    auto-named QuantDense_i submodules; explicit names are unchanged."""
    return tuple(
        p.replace("QuantDense_", "Dense_") if p.startswith("QuantDense_") else p
        for p in path
    )


def quantize_params(dalle_quant, params, example_text, example_image) -> Dict[str, Any]:
    """Build the quantized parameter tree for ``dalle_quant``
    (a DALLE with serve_quant=True) from trained ``params``."""
    target = jax.eval_shape(
        dalle_quant.init, jax.random.key(0), example_text, example_image
    )["params"]
    flat_t = traverse_util.flatten_dict(target)
    flat_s = traverse_util.flatten_dict(params)

    out: Dict[Tuple[str, ...], Any] = {}
    quant_cache: Dict[Tuple[str, ...], Tuple[np.ndarray, np.ndarray]] = {}

    def quantized(src_path: Tuple[str, ...], fn):
        if src_path not in quant_cache:
            quant_cache[src_path] = fn(np.asarray(flat_s[src_path]))
        return quant_cache[src_path]

    for path, spec in flat_t.items():
        src = _src_path(path)
        if path[-1] == "kernel_q":
            q, _ = quantized(src[:-1] + ("kernel",), quantize_kernel)
            assert q.shape == spec.shape, (path, q.shape, spec.shape)
            out[path] = jnp.asarray(q)
        elif path[-1] == "embedding_q":
            q, _ = quantized(src[:-1] + ("embedding",), quantize_embedding)
            assert q.shape == spec.shape, (path, q.shape, spec.shape)
            out[path] = jnp.asarray(q)
        elif path[-1] == "scale" and (path[:-1] + ("kernel_q",)) in flat_t:
            _, s = quantized(src[:-1] + ("kernel",), quantize_kernel)
            out[path] = jnp.asarray(s)
        elif path[-1] == "scale" and (path[:-1] + ("embedding_q",)) in flat_t:
            _, s = quantized(src[:-1] + ("embedding",), quantize_embedding)
            out[path] = jnp.asarray(s)
        else:
            leaf = flat_s[src]
            assert leaf.shape == spec.shape, (path, leaf.shape, spec.shape)
            out[path] = leaf
    return traverse_util.unflatten_dict(out)


def quantize_dalle(dalle, params, batch_size: int = 1):
    """(dalle, trained params) -> (serve_quant dalle, int8 params) ready for
    ``models/sampling.py`` decode. Dense projections and the token-embedding
    tables are quantized; MoE expert banks and gMLP blocks pass through at
    full precision (pinned by tests/test_quantize.py)."""
    dalle_q = dalle.clone(serve_quant=True)
    text = jnp.zeros((batch_size, dalle.text_seq_len), jnp.int32)
    image = jnp.zeros((batch_size, dalle.image_seq_len), jnp.int32)
    return dalle_q, quantize_params(dalle_q, params, text, image)


def prepare_for_serving(dalle, params, int8: bool = False, batch_size: int = 1):
    """Standard serving transform: cast the model + f32 params to bf16
    (decode is HBM-bound on weight reads) and optionally quantize the Dense
    kernels to int8. The single home for the load sequence generate.py and
    bench.py share."""
    dalle = dalle.clone(dtype=jnp.bfloat16)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        params,
    )
    if int8:
        dalle, params = quantize_dalle(dalle, params, batch_size=batch_size)
    return dalle, params
