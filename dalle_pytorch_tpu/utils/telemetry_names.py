"""The single registry of telemetry names (docs/DESIGN.md §9 and §11).

Every counter, gauge, histogram, span, and event name the package emits
is declared here, per kind — one dot-separated namespace per subsystem
(``serve.*`` engine, ``router.*`` front door, ``train.*`` trainer,
``data.*``/``webdata.*`` loaders, ``download.*`` fetcher,
``telemetry.*`` the layer itself). The static checker
(``tools/lint.py``, finding DTL041) flags any literal passed to
``counters.inc`` / ``gauges.set`` / ``histograms.observe`` /
``TELEMETRY.span|begin|event`` that is not registered under the matching
kind, and DTL042 flags registered names missing from the DESIGN.md §9
tables — so the registry, the code, and the operator docs cannot drift.

This module is parsed by AST (never imported) by the linter, so keep the
sets as flat literals. It is also importable at runtime (host-side only,
like the rest of the observability layer) for tools and tests that want
to validate names programmatically.

Dynamic names: a handful of call sites build names from enum values
(``f"serve.{outcome.value}"``). Their full expansions are registered
here explicitly — the checker validates the f-string's literal head
against the registered names, so a renamed namespace still fails lint
while a new enum member only needs its expansion added here.

Span-duration histograms (``<span>_s``, auto-observed by
utils/telemetry.py) are derived — see ``SPAN_DURATION_HISTOGRAMS`` —
and are valid histogram names wherever bench/tools read them.
"""

from __future__ import annotations

# --------------------------------------------------------------- spans

SPANS = frozenset({
    # serving engine (serving/engine.py)
    "serve.request",        # submit -> typed outcome (the lifecycle span)
    "serve.prefill",        # monolithic, or cross-iteration when chunked
    "serve.prefill_chunk",  # one per chunk, synced in-span
    "serve.slot_insert",
    "serve.decode_step",    # one per DISPATCHED decode step (split mode)
    "serve.iteration",      # one per fused ragged iteration (one dispatch)
    "serve.spec_verify",    # one per speculative iteration: draft+verify+
                            # accept dispatch and its synchronous readback
    # post-decode pipeline (serving/postdecode.py): one span per batched
    # stage dispatch — the auto "<span>_s" histograms ARE the per-stage
    # latency distributions
    "serve.stage.vae_decode",
    "serve.stage.clip_rerank",
    # replicated front door (serving/router.py)
    "router.request",       # router submit -> typed outcome
    # trainer (train_dalle.py)
    "train.step",           # dispatch -> verdict (device-inclusive)
    "train.data_wait",
    "train.ckpt_save",
})

# -------------------------------------------------------------- events

EVENTS = frozenset({
    # serving engine
    "serve.admit",
    "serve.first_token",
    "serve.evict",
    "serve.decode_stall",
    "serve.prefill_retry",
    "serve.prefix_hit",      # admission mapped >=1 cached prompt page
    "serve.snapshot_reject", # prefix snapshot failed verify-on-load
    # adaptive control loop (serving/control.py): one per controller
    # evaluation, carrying its input vitals and output knobs — the
    # audit/replay record (DESIGN.md §8.6)
    "serve.control.decision",
    # replicated front door
    "router.respawn",        # dead replica rebuilt and readmitted HEALTHY
    "router.respawn_fail",   # a respawn attempt failed (or exhausted)
    "router.shed",
    "router.drain",
    "router.drained",
    "router.failover",
    "router.failover_dispatch",
    "router.invariant_violation",
    "router.breaker_open",
    "router.readmit",
    # trainer
    "train.nan_skip",
    "train.nan_abort",
    "train.preempt_signal",
    # data loaders (data/webdata.py)
    "data.shard_open",
    "data.shard_quarantined",
    "data.shard_abort",
})

# ------------------------------------------------------------ counters

COUNTERS = frozenset({
    # serving engine lifecycle
    "serve.submitted",
    "serve.admitted",
    "serve.completed",
    "serve.rejected",
    # typed-outcome tallies (f"serve.{outcome.value}" expansions)
    "serve.deadline_exceeded",
    "serve.cancelled",
    "serve.preempt_cap",
    "serve.prefill_failed",
    "serve.completed_tokens_only",
    "serve.completed_unranked",
    # typed-reject tallies (f"serve.rejected.{reason.value}" expansions)
    "serve.rejected.demand_exceeds_pool",
    "serve.rejected.queue_full",
    "serve.rejected.no_replica",
    # engine work/robustness tallies
    "serve.clamped",
    "serve.preempted",
    "serve.decode_steps",
    "serve.dispatches",     # model-jit dispatches (fused: 1/iteration)
    "serve.prefill_chunks",
    "serve.prefill_retries",
    "serve.fault_request_cancel",
    "serve.fault_prefill_fail",
    "serve.fault_decode_stall",
    "serve.fault_page_exhaust",
    "serve.fault_prefix_hash_collide",
    "serve.fault_prefix_publish_fail",
    "serve.fault_spec_verify_abort",
    "serve.fault_journal_torn",
    "serve.fault_snapshot_corrupt",
    "serve.fault_vae_decode_fail",
    "serve.fault_rerank_fail",
    "serve.fault_stage_timeout",
    "serve.fault_control_stall",
    # adaptive control loop (serving/control.py; DESIGN.md §8.6)
    "serve.control.decisions",    # controller evaluations run
    "serve.control.adjustments",  # evaluations that changed >=1 knob
    "serve.control.stalls",       # evaluations degraded to static defaults
    # post-decode pipeline (serving/postdecode.py; DESIGN.md §8.5)
    "serve.stage.enqueued",        # requests entering the pipeline
    "serve.stage.vae_images",      # VAE_DECODE stage completions (images)
    "serve.stage.reranked",        # CLIP_RERANK stage completions (scores)
    "serve.stage.retries",         # failed stage attempts backed off
    "serve.stage.timeouts",        # dispatches past the stage time budget
    "serve.stage.degraded",        # typed-degraded completions (both kinds)
    "serve.stage.journal_records", # stage-boundary WAL records written
    # crash recovery (serving/journal.py + engine snapshot; §8.3)
    "serve.journal.appended",   # admitted-request WAL records written
    "serve.journal.replayed",   # unfinished requests resubmitted on restart
    "serve.journal.torn",       # torn tail records detected and dropped
    "serve.snapshot.saved",     # prefix-cache snapshots committed to disk
    "serve.snapshot.restored",  # snapshots verified and restored (warm start)
    "serve.snapshot.rejected",  # snapshots refused by verify-on-load
    # speculative decoding (serving/engine.py:_spec_iteration)
    "serve.spec.drafted",     # draft tokens proposed to verify rows
    "serve.spec.accepted",    # drafts committed by exact-match acceptance
    "serve.spec.rejected",    # drafts discarded (rolled back)
    "serve.spec.fallbacks",   # iterations degraded to plain decode
    # cross-request prefix cache (serving/prefix_cache.py)
    "serve.prefix.hits",          # probes matching >=1 page
    "serve.prefix.misses",        # probes matching nothing
    "serve.prefix.pages_hit",     # cached pages mapped/copied at admission
    "serve.prefix.pages_deduped", # publish-side pages already indexed
    "serve.prefix.cow_copies",    # shared terminal pages privatized
    "serve.prefix.published",     # pages newly committed to the index
    "serve.prefix.evictions",     # LRU index evictions (budget/arena)
    "serve.prefix.publish_skips", # fail-open publishes (arena/budget full)
    # replicated front door
    "router.submitted",
    "router.shed",
    "router.drains",
    "router.drained",
    "router.readmits",
    "router.breaker_opens",
    "router.replica_deaths",
    "router.failovers",
    "router.no_replica",
    "router.fault_replica_crash",
    "router.fault_replica_stall",
    "router.fault_health_flap",
    "router.fault_replica_respawn_fail",
    "router.respawns",          # dead replicas rebuilt and readmitted
    # typed-outcome tallies (f"router.{outcome.value}" expansions)
    "router.completed",
    "router.rejected",
    "router.deadline_exceeded",
    "router.cancelled",
    "router.preempt_cap",
    "router.prefill_failed",
    "router.completed_tokens_only",
    "router.completed_unranked",
    # trainer
    "train.nan_skips",
    # data paths (the webdata.* names data.* events carry; DESIGN.md §8)
    "webdata.decode_errors",
    "webdata.shard_open_retries",
    "webdata.shards_quarantined",
    "webdata.shards_opened",
    "webdata.quarantined_skips",
    "webdata.shard_aborts",
    "download.retries",
    "download.failures",
    # the telemetry layer's self-accounting
    "telemetry.dropped",
    "telemetry.sink_errors",
})

# -------------------------------------------------------------- gauges

GAUGES = frozenset({
    "serve.pool_occupancy",
    "serve.running",
    "serve.prefilling",
    "serve.queued",
    "serve.stage.queued",        # requests parked in the post-decode pipeline
    "serve.prefix_hit_frac",     # hits / (hits + misses), lifetime
    "serve.prefix_pages",        # pages currently held by the index
    "serve.spec_accept_frac",    # accepted / drafted, lifetime
    # KV storage-format footprint (quantized-KV capacity lever, §6.1):
    # bytes of K/V storage (content + scale pools) per slot row, and
    # total physical pages per pool (slots + prefix arena) — int8 pools
    # roughly halve bytes_per_slot, which is the ~2x pages-at-fixed-HBM
    # headline bench.py --serve asserts
    "serve.kv_quant.bytes_per_slot",
    "serve.kv_quant.pages",
    # engine vitals: sliding-window reductions over existing metrics
    # (utils/vitals.py; DESIGN.md §8.6) — the controller's inputs
    "serve.vitals.spec_accept_rate",    # windowed accepted/drafted
    "serve.vitals.prefix_hit_frac",     # windowed hits/(hits+misses)
    "serve.vitals.decode_gap_s",        # windowed max inter-iteration gap
    "serve.vitals.stage_lag",           # windowed mean post-decode depth
    "serve.vitals.deadline_miss_rate",  # windowed misses/terminations
    "serve.vitals.occupancy",           # windowed mean pool occupancy
    "serve.vitals.roofline_frac",       # iteration FLOPs/s vs device peak
    # effective knob levels the control loop last applied
    "serve.control.spec_k",
    "serve.control.budget",
    "serve.control.watermark",
    "serve.control.prefix_pages_target",
    "router.queued",
    "router.fleet_occupancy",
    "router.replicas_live",
    "router.replica_state_code",
})

# ---------------------------------------------------------- histograms

HISTOGRAMS = frozenset({
    "serve.queue_wait_s",
    "serve.ttft_s",
    "serve.request_latency_s",
    "serve.completed_latency_s",
    # request -> image end-to-end latency: submit to full-pipeline DONE
    # (image-bearing completions only; DESIGN.md §8.5)
    "serve.stage.request_to_image_s",
    "router.failover_latency_s",
    # TTFT split by prefix-cache hit class (serve.ttft_s still carries
    # every request; bench's cached-vs-cold comparison reads these)
    "serve.ttft_full_hit_s",
    "serve.ttft_partial_hit_s",
    "serve.ttft_cold_s",
    # tokens committed per speculative verify step (1 .. spec_k+1); the
    # bench's accepted-tokens-per-step distribution reads this
    "serve.spec_accepted_per_step",
    # replica kill -> healthy-again (respawn) MTTR, per replica label —
    # the bench recovery record's source
    "serve.recovery_s",
    # backoff hints attached to load-typed rejections (queue_full /
    # no_replica): what the fleet told clients to wait — the traffic
    # sim's storm-amplification guard reads this distribution
    "router.retry_after_s",
})

# span durations are auto-observed as "<span>_s" (utils/telemetry.py);
# derived here so readers (bench latency splits) can validate against it
SPAN_DURATION_HISTOGRAMS = frozenset(s + "_s" for s in SPANS)

ALL_NAMES = SPANS | EVENTS | COUNTERS | GAUGES | HISTOGRAMS

_KINDS = {
    "span": SPANS,
    "event": EVENTS,
    "counter": COUNTERS,
    "gauge": GAUGES,
    "histogram": HISTOGRAMS | SPAN_DURATION_HISTOGRAMS,
}


def is_registered(name: str, kind: str = None) -> bool:
    """True iff ``name`` is registered (optionally under ``kind`` in
    span/event/counter/gauge/histogram)."""
    if kind is None:
        return name in ALL_NAMES or name in SPAN_DURATION_HISTOGRAMS
    return name in _KINDS[kind]
