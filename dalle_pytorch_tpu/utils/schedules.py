"""Host-side learning-rate controllers.

The reference uses torch's stateful schedulers: ReduceLROnPlateau on the
DALL-E trainer (train_dalle.py:429-441) and ExponentialLR on the VAE trainer
(train_vae.py:150-151). In the functional JAX design the *controller* stays on
the host (tiny state, checkpointable via state_dict) and emits a plain float
that the compiled train step takes as a traced argument — no recompile on lr
change, no optimizer rebuild.
"""

from __future__ import annotations

import math
from typing import Optional


class ReduceLROnPlateau:
    """lr *= factor after ``patience`` non-improving metrics (torch semantics
    with min mode, the reference's configuration, train_dalle.py:430-437)."""

    def __init__(
        self,
        lr: float,
        factor: float = 0.5,
        patience: int = 10,
        cooldown: int = 10,
        min_lr: float = 1e-6,
        threshold: float = 1e-4,
    ):
        self.lr = lr
        self.factor = factor
        self.patience = patience
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.threshold = threshold
        self.best = math.inf
        self.num_bad = 0
        self.cooldown_counter = 0

    def step(self, metric: float) -> float:
        # torch's exact step order (lr_scheduler.ReduceLROnPlateau.step):
        # improvement test, THEN the cooldown decrement (which runs on every
        # in-cooldown step — including improving ones — and zeroes the bad
        # count), then the reduction check
        if metric < self.best * (1 - self.threshold):
            self.best = metric
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self.num_bad > self.patience:
            self.lr = max(self.lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        return self.lr

    def state_dict(self) -> dict:
        return {
            "lr": self.lr,
            "best": self.best,
            "num_bad": self.num_bad,
            "cooldown_counter": self.cooldown_counter,
        }

    def load_state_dict(self, d: dict) -> None:
        self.lr = float(d["lr"])
        self.best = float(d["best"])
        self.num_bad = int(d["num_bad"])
        self.cooldown_counter = int(d["cooldown_counter"])


class ExponentialDecay:
    """lr *= gamma per epoch (train_vae.py:150-151)."""

    def __init__(self, lr: float, gamma: float = 0.98):
        self.lr = lr
        self.gamma = gamma

    def step(self, metric: Optional[float] = None) -> float:
        self.lr *= self.gamma
        return self.lr

    def state_dict(self) -> dict:
        return {"lr": self.lr}

    def load_state_dict(self, d: dict) -> None:
        self.lr = float(d["lr"])


class ConstantLR:
    def __init__(self, lr: float):
        self.lr = lr

    def step(self, metric: Optional[float] = None) -> float:
        return self.lr

    def state_dict(self) -> dict:
        return {"lr": self.lr}

    def load_state_dict(self, d: dict) -> None:
        self.lr = float(d["lr"])


def gumbel_temperature(step: int, t0: float, anneal_rate: float, t_min: float) -> float:
    """temp = max(t0 * exp(-rate * step), t_min), updated every 100 steps in
    the reference (train_vae.py:269-271)."""
    return max(t0 * math.exp(-anneal_rate * step), t_min)
