"""Checkpoint store — dual-format, mirroring the reference's semantics
(SURVEY.md §5.4).

Plain format (reference train_dalle.py:514-519 ``torch.save`` of
``{hparams, vae_params, epoch, weights, opt_state, scheduler_state}``):
one msgpack file holding json-encoded hparams plus the numpy-ified state
pytree — readable on any host, no framework pickle.

Sharded format (reference DeepSpeed ``save_checkpoint`` into a ``-ds-cp/``
dir, train_dalle.py:520-544): an orbax directory checkpoint that writes each
host's addressable shards in parallel — the right format for fsdp/tp-sharded
TrainStates — plus the same ``aux.json`` hparams sidecar the reference keeps
in ``auxiliary.pt``. Rotation keeps the newest N step dirs
(cp_files_to_keep, train_dalle.py:523-526).

Directory saves are two-phase committed (docs/DESIGN.md §9): after orbax
finishes, every file in the step dir is checksummed into ``MANIFEST.json``
and a ``COMMITTED`` marker lands last. ``load_sharded_checkpoint`` restores
only verified step dirs and falls back to the newest verified one — a crash
mid-save (or bit corruption on the newest dir) costs at most the steps since
the previous verified save, never a poisoned restore.
"""

from __future__ import annotations

import json
import shutil
import sys
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from .faults import FAULTS
from .resilience import (
    COMMIT_NAME,
    FILE_MANIFEST_SUFFIX,
    verify_dir_manifest,
    verify_file_manifest,
    write_dir_manifest,
    write_file_manifest,
)

_HEADER_KEY = "__dalle_tpu_meta__"


class CheckpointError(RuntimeError):
    """Typed load failure: missing, torn, or corrupt checkpoint. CLIs catch
    this and exit nonzero with the reason instead of surfacing a msgpack
    stack trace (or, pre-manifest, silently deserializing garbage)."""


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def save_checkpoint(path: str, state: Any, meta: Optional[dict] = None) -> None:
    """Plain single-file save: msgpack of {meta-json, state} with every leaf
    a host numpy array (gathers sharded arrays — use the sharded format for
    models that don't fit one host)."""
    payload = {
        _HEADER_KEY: json.dumps(meta or {}),
        "state": serialization.to_state_dict(_to_host(state)),
    }
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(p.suffix + ".tmp")
    tmp.write_bytes(serialization.msgpack_serialize(payload))
    # invalidate any PREVIOUS save's sidecar before the content swap: a
    # crash between replace and the new sidecar must leave "no manifest"
    # (unverified but loadable), never a stale manifest describing the old
    # bytes that would condemn a perfectly good new file as corrupt
    Path(str(p) + FILE_MANIFEST_SUFFIX).unlink(missing_ok=True)
    tmp.replace(p)  # atomic: never leave a torn checkpoint
    # sha256+size sidecar, written last (single-file two-phase commit):
    # serving loads verify against it instead of trusting the file
    write_file_manifest(p)


def load_checkpoint(path: str, target: Any = None) -> tuple[Any, dict]:
    """-> (state, meta). With ``target`` (a template pytree) the state is
    restored into that structure; otherwise a raw nested dict is returned."""
    raw = serialization.msgpack_restore(Path(path).read_bytes())
    meta = json.loads(raw.pop(_HEADER_KEY, "{}"))
    state = raw["state"]
    if target is not None:
        state = serialization.from_state_dict(target, state)
    return state, meta


def check_checkpoint_file(path: str, require_manifest: bool = False) -> None:
    """Refuse a missing/torn/corrupt plain checkpoint BEFORE deserializing
    it — raises ``CheckpointError`` with the manifest verifier's reason.

    Serving entry points (generate.py) call this instead of
    ``assert Path(...).exists()``: an existence check happily loads a file
    truncated by a crashed save or bit-rotted in transit. A checkpoint
    without a sidecar (saved pre-manifest) passes with a stderr warning
    unless ``require_manifest``; msgpack parse errors downstream still
    surface, they are just no longer the FIRST line of defense."""
    ok, reason = verify_file_manifest(path)
    if ok:
        return
    if reason == "no manifest" and not require_manifest:
        print(
            f"WARNING: {path} has no manifest sidecar (pre-manifest save); "
            "loading unverified", file=sys.stderr,
        )
        return
    raise CheckpointError(f"checkpoint {path}: {reason}")


# ----------------------------------------------------------- sharded format


def save_sharded_checkpoint(
    ckpt_dir: str,
    step: int,
    state: Any,
    meta: Optional[dict] = None,
    keep_n: Optional[int] = None,
) -> str:
    """Write ``<ckpt_dir>/step_<n>/`` via orbax (each host writes its own
    shards), checksum+commit it, refresh the ``aux.json`` hparams sidecar
    (atomically — a crash mid-write must not take out the resume metadata
    for every older step), and rotate old step dirs."""
    import orbax.checkpoint as ocp

    root = Path(ckpt_dir)
    root.mkdir(parents=True, exist_ok=True)
    target = (root / f"step_{step:08d}").resolve()
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(target, state, force=True)
    # manifest/sidecar/rotation are single-writer: the orbax save above is
    # the collective part (and synchronizes hosts); N hosts writing the
    # same MANIFEST.json.tmp on a shared filesystem would race a truncated
    # manifest into a COMMITTED dir
    if jax.process_index() == 0:
        # meta rides in the manifest too: on fallback to an older step the
        # restored meta must describe THAT step, not the newest aux.json
        # write
        write_dir_manifest(target, extra={"step": step, "meta": meta or {}})
        if FAULTS.take("ckpt_corrupt"):
            _corrupt_one_file(target)
        aux = root / "aux.json"
        tmp = aux.with_suffix(".json.tmp")
        tmp.write_text(json.dumps({"meta": meta or {}, "latest": step}))
        tmp.replace(aux)

        if keep_n is not None:
            # rotation counts only COMMITTED dirs — a torn leftover must
            # not push the last good fallback out of the window. Torn dirs
            # (no marker; crash-mid-save debris of the two-phase design)
            # are junk and get pruned outright. Marker presence is cheap;
            # full checksums stay a load-time concern.
            committed, torn = [], []
            for d in sorted(root.glob("step_*")):
                (committed if (d / COMMIT_NAME).exists() else torn).append(d)
            for old in torn + committed[:-keep_n]:
                shutil.rmtree(old, ignore_errors=True)
    return str(target)


def _corrupt_one_file(step_dir: Path) -> None:
    """ckpt_corrupt fault: flip bytes in the largest payload file AFTER the
    manifest committed — models post-commit bit rot / torn replication, the
    case only checksum verification catches (a missing commit marker is the
    easier torn-save case)."""
    payload = [
        p for p in step_dir.rglob("*")
        if p.is_file() and p.name not in ("MANIFEST.json", "COMMITTED")
    ]
    victim = max(payload, key=lambda p: p.stat().st_size)
    data = bytearray(victim.read_bytes())
    for i in range(min(64, len(data))):
        data[i] ^= 0xFF
    victim.write_bytes(data)
    print(f"fault ckpt_corrupt: flipped bytes in {victim}", file=sys.stderr)


def verify_step_dir(step_dir: str) -> tuple[bool, str]:
    """-> (ok, reason): commit marker present and every manifested file
    passes size+sha256. The operator CLI is ``tools/verify_ckpt.py``."""
    return verify_dir_manifest(step_dir)


def latest_verified_step(ckpt_dir: str) -> Optional[int]:
    """Newest step number whose dir verifies; None when none do (or the
    dir doesn't exist) — the trainer's resume probe."""
    root = Path(ckpt_dir)
    if not root.is_dir():
        return None
    for path in sorted(root.glob("step_*"), reverse=True):
        ok, _ = verify_dir_manifest(path)
        if ok:
            return int(path.name.split("_")[1])
    return None


def load_sharded_checkpoint(
    ckpt_dir: str,
    target: Any,
    step: Optional[int] = None,
    shardings: Any = None,
    verify: bool = True,
) -> tuple[Any, dict, int]:
    """Restore the newest VERIFIED (or given) step dir into ``target``'s
    structure, placing leaves with ``shardings`` when given.
    -> (state, meta, step).

    Torn/corrupt step dirs are skipped with a warning and the newest
    verified one wins — the pre-manifest behavior (``steps[-1]``) happily
    restored a half-written dir left by a crash mid-save. An explicitly
    requested ``step`` must itself verify; ``verify=False`` skips that
    re-hash ONLY for a step the caller just verified (the trainer's
    resume probe — checksumming a multi-GB checkpoint twice per launch
    is real time)."""
    import orbax.checkpoint as ocp

    root = Path(ckpt_dir)
    aux = json.loads((root / "aux.json").read_text()) if (root / "aux.json").exists() else {}
    if step is None:
        steps = sorted(root.glob("step_*"), reverse=True)
        assert steps, f"no step_* checkpoints under {ckpt_dir}"
        path = None
        for cand in steps:
            ok, reason = verify_dir_manifest(cand)
            if ok:
                path = cand.resolve()
                break
            print(
                f"checkpoint {cand.name} skipped: {reason}", file=sys.stderr
            )
        assert path is not None, (
            f"no verified step_* checkpoint under {ckpt_dir} "
            f"({len(steps)} dirs present, all torn/corrupt — "
            "run tools/verify_ckpt.py for per-file detail)"
        )
        step = int(path.name.split("_")[1])
    else:
        path = (root / f"step_{step:08d}").resolve()
        if verify:
            ok, reason = verify_dir_manifest(path)
            assert ok, f"requested checkpoint {path} failed verification: {reason}"

    if shardings is not None:
        abstract = jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            target,
            shardings,
        )
        args = __import__("orbax.checkpoint", fromlist=["args"]).args
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path, args=args.PyTreeRestore(item=abstract))
    else:
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path, item=target)
    try:
        meta = json.loads((path / "MANIFEST.json").read_text()).get("meta")
    except (OSError, ValueError):
        meta = None
    if meta is None:
        meta = aux.get("meta", {})
    return state, meta, step
