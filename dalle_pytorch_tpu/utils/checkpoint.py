"""Checkpoint store — dual-format, mirroring the reference's semantics
(SURVEY.md §5.4).

Plain format (reference train_dalle.py:514-519 ``torch.save`` of
``{hparams, vae_params, epoch, weights, opt_state, scheduler_state}``):
one msgpack file holding json-encoded hparams plus the numpy-ified state
pytree — readable on any host, no framework pickle.

Sharded format (reference DeepSpeed ``save_checkpoint`` into a ``-ds-cp/``
dir, train_dalle.py:520-544): an orbax directory checkpoint that writes each
host's addressable shards in parallel — the right format for fsdp/tp-sharded
TrainStates — plus the same ``aux.json`` hparams sidecar the reference keeps
in ``auxiliary.pt``. Rotation keeps the newest N step dirs
(cp_files_to_keep, train_dalle.py:523-526).
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

_HEADER_KEY = "__dalle_tpu_meta__"


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def save_checkpoint(path: str, state: Any, meta: Optional[dict] = None) -> None:
    """Plain single-file save: msgpack of {meta-json, state} with every leaf
    a host numpy array (gathers sharded arrays — use the sharded format for
    models that don't fit one host)."""
    payload = {
        _HEADER_KEY: json.dumps(meta or {}),
        "state": serialization.to_state_dict(_to_host(state)),
    }
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(p.suffix + ".tmp")
    tmp.write_bytes(serialization.msgpack_serialize(payload))
    tmp.replace(p)  # atomic: never leave a torn checkpoint


def load_checkpoint(path: str, target: Any = None) -> tuple[Any, dict]:
    """-> (state, meta). With ``target`` (a template pytree) the state is
    restored into that structure; otherwise a raw nested dict is returned."""
    raw = serialization.msgpack_restore(Path(path).read_bytes())
    meta = json.loads(raw.pop(_HEADER_KEY, "{}"))
    state = raw["state"]
    if target is not None:
        state = serialization.from_state_dict(target, state)
    return state, meta


# ----------------------------------------------------------- sharded format


def save_sharded_checkpoint(
    ckpt_dir: str,
    step: int,
    state: Any,
    meta: Optional[dict] = None,
    keep_n: Optional[int] = None,
) -> str:
    """Write ``<ckpt_dir>/step_<n>/`` via orbax (each host writes its own
    shards) plus an ``aux.json`` hparams sidecar; rotate old step dirs."""
    import orbax.checkpoint as ocp

    root = Path(ckpt_dir)
    root.mkdir(parents=True, exist_ok=True)
    target = (root / f"step_{step:08d}").resolve()
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(target, state, force=True)
    (root / "aux.json").write_text(json.dumps({"meta": meta or {}, "latest": step}))

    if keep_n is not None:
        steps = sorted(root.glob("step_*"))
        for old in steps[:-keep_n]:
            shutil.rmtree(old, ignore_errors=True)
    return str(target)


def load_sharded_checkpoint(
    ckpt_dir: str,
    target: Any,
    step: Optional[int] = None,
    shardings: Any = None,
) -> tuple[Any, dict, int]:
    """Restore the newest (or given) step dir into ``target``'s structure,
    placing leaves with ``shardings`` when given. -> (state, meta, step)."""
    import orbax.checkpoint as ocp

    root = Path(ckpt_dir)
    aux = json.loads((root / "aux.json").read_text()) if (root / "aux.json").exists() else {}
    if step is None:
        steps = sorted(root.glob("step_*"))
        assert steps, f"no step_* checkpoints under {ckpt_dir}"
        path = steps[-1].resolve()
        step = int(path.name.split("_")[1])
    else:
        path = (root / f"step_{step:08d}").resolve()

    if shardings is not None:
        abstract = jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            target,
            shardings,
        )
        args = __import__("orbax.checkpoint", fromlist=["args"]).args
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path, args=args.PyTreeRestore(item=abstract))
    else:
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path, item=target)
    return state, aux.get("meta", {}), step
