"""ctypes binding for the native C++ BPE engine (native/bpe_tokenizer.cc).

``NativeSimpleTokenizer`` is a drop-in for ``SimpleTokenizer`` (same vocab,
same tokenize/encode/decode contract, byte-exact outputs — parity-tested in
tests/test_native_bpe.py) with the scanner + merge loop running natively.
Text cleaning (ftfy/NFC, html unescape, whitespace collapse, lowercase) stays
in Python so both tokenizers share data/tokenizers.py's exact preprocessing.
"""

from __future__ import annotations

import ctypes
from typing import Iterable, List, Optional

import numpy as np

from .tokenizers import (
    _TokenizeMixin,
    basic_clean,
    default_bpe_path,
    whitespace_clean,
)

_lib = None
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    from ..native.build import build

    so = build()
    if so is None:
        _lib_failed = True
        return None
    lib = ctypes.CDLL(str(so))
    lib.bpe_new.restype = ctypes.c_void_p
    lib.bpe_new.argtypes = [ctypes.c_char_p]
    lib.bpe_free.argtypes = [ctypes.c_void_p]
    lib.bpe_vocab_size.restype = ctypes.c_int32
    lib.bpe_vocab_size.argtypes = [ctypes.c_void_p]
    lib.bpe_encode.restype = ctypes.c_int64
    lib.bpe_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]
    lib.bpe_decode.restype = ctypes.c_int64
    lib.bpe_decode.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64,
    ]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load_lib() is not None


class NativeSimpleTokenizer(_TokenizeMixin):
    """CLIP byte-level BPE backed by the C++ engine."""

    def __init__(self, bpe_path: Optional[str] = None):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError(
                "native BPE engine unavailable (no C++ toolchain?); use "
                "SimpleTokenizer instead"
            )
        bpe_path = bpe_path or default_bpe_path()
        if bpe_path is None:
            raise FileNotFoundError("BPE merges file not found")
        self._lib = lib
        self._h = lib.bpe_new(bpe_path.encode())
        if not self._h:
            raise RuntimeError(f"native BPE engine failed to load {bpe_path}")
        self.vocab_size = int(lib.bpe_vocab_size(self._h))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.bpe_free(h)
            self._h = None

    def encode(self, text: str) -> List[int]:
        text = whitespace_clean(basic_clean(text)).lower()
        raw = text.encode("utf-8")
        cap = max(len(raw) * 2, 64)
        while True:
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.bpe_encode(self._h, raw, len(raw), buf, cap)
            if n <= cap:
                return list(buf[:n])
            cap = int(n)

    def decode(self, tokens: Iterable[int], pad_tokens: set = frozenset()) -> str:
        ids = np.asarray([int(t) for t in tokens], np.int32)
        skip = np.asarray(sorted(int(t) for t in pad_tokens), np.int32)
        ids_p = ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        skip_p = skip.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        cap = max(len(ids) * 16, 64)
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.bpe_decode(
                self._h, ids_p, len(ids), skip_p, len(skip), buf, cap
            )
            if n <= cap:
                return buf.raw[:n].decode("utf-8", errors="replace")
            cap = int(n)
