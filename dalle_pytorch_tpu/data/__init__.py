from .loader import (
    DataLoader,
    ImageFolderDataset,
    TextImageDataset,
    image_to_array,
    random_resized_crop,
)
from .tokenizers import (
    ChineseTokenizer,
    HugTokenizer,
    SimpleTokenizer,
    YttmTokenizer,
    default_bpe_path,
    get_tokenizer,
)
from .webdata import TarImageTextDataset, TarLoader, expand_urls

__all__ = [
    "ChineseTokenizer",
    "DataLoader",
    "HugTokenizer",
    "ImageFolderDataset",
    "SimpleTokenizer",
    "TarImageTextDataset",
    "TarLoader",
    "TextImageDataset",
    "YttmTokenizer",
    "default_bpe_path",
    "expand_urls",
    "get_tokenizer",
    "image_to_array",
    "random_resized_crop",
]
