"""Folder dataset + host-side data loading.

Re-owns the reference's ``TextImageDataset`` (loader.py:10-99): images paired
with same-stem ``.txt`` caption files, one random caption per sample, a
1:1-ratio RandomResizedCrop, and corrupt-file resilience (skip to a
random/next sample on decode error, loader.py:58-69,79-96).

TPU-shaped differences: samples come out as numpy NHWC float32 in [0, 1]
(batch crosses the host->device boundary once, as one array), the loader
shards deterministically across hosts (replacing torch's DistributedSampler,
train_dalle.py:391-398), and batching runs in a background prefetch thread so
host decode overlaps device compute.
"""

from __future__ import annotations

import queue
import random
import threading
from pathlib import Path
from typing import Iterator, List, Optional, Tuple

import numpy as np
from PIL import Image

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def random_resized_crop(
    img: Image.Image,
    out_size: int,
    rng: random.Random,
    min_scale: float = 0.75,
) -> Image.Image:
    """Square random crop covering a random [min_scale, 1] area fraction,
    resized to out_size (reference loader.py:46-53: RandomResizedCrop with
    ratio (1, 1) and scale (resize_ratio, 1))."""
    w, h = img.size
    area = w * h
    for _ in range(10):
        target = rng.uniform(min_scale, 1.0) * area
        side = int(round(target**0.5))
        if side <= w and side <= h:
            left = rng.randint(0, w - side)
            top = rng.randint(0, h - side)
            img = img.crop((left, top, left + side, top + side))
            break
    else:  # degenerate aspect ratios: center-crop the largest square
        side = min(w, h)
        left, top = (w - side) // 2, (h - side) // 2
        img = img.crop((left, top, left + side, top + side))
    return img.resize((out_size, out_size), Image.BICUBIC)


def image_to_array(img: Image.Image) -> np.ndarray:
    """RGB(A)/L -> (h, w, 3) float32 in [0, 1] (the reference's ToTensor,
    NHWC instead of NCHW)."""
    img = img.convert("RGB")
    return np.asarray(img, dtype=np.float32) / 255.0


class TextImageDataset:
    def __init__(
        self,
        folder: str,
        text_len: int = 256,
        image_size: int = 128,
        truncate_captions: bool = False,
        resize_ratio: float = 0.75,
        tokenizer=None,
        shuffle: bool = False,
        seed: int = 0,
    ):
        self.shuffle = shuffle
        path = Path(folder)

        text_files = {p.stem: p for p in path.glob("**/*.txt")}
        image_files = {
            p.stem: p for ext in IMAGE_EXTS for p in path.glob(f"**/*{ext}")
        }
        keys = image_files.keys() & text_files.keys()
        self.keys = sorted(keys)
        self.text_files = {k: text_files[k] for k in self.keys}
        self.image_files = {k: image_files[k] for k in self.keys}
        self.text_len = text_len
        self.truncate_captions = truncate_captions
        self.resize_ratio = resize_ratio
        self.image_size = image_size
        if tokenizer is None:
            from .tokenizers import get_tokenizer

            tokenizer = get_tokenizer()
        self.tokenizer = tokenizer
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        return len(self.keys)

    def random_sample(self):
        return self[self._rng.randint(0, len(self) - 1)]

    def sequential_sample(self, ind: int):
        return self[(ind + 1) % len(self)]

    def skip_sample(self, ind: int):
        if self.shuffle:
            return self.random_sample()
        return self.sequential_sample(ind)

    def __getitem__(self, ind: int) -> Tuple[np.ndarray, np.ndarray]:
        key = self.keys[ind]
        try:
            descriptions = [
                d for d in
                self.text_files[key].read_text(encoding="utf8").split("\n") if d
            ]
            description = self._rng.choice(descriptions)  # IndexError if empty
            tokens = self.tokenizer.tokenize(
                description, self.text_len, truncate_text=self.truncate_captions
            )[0]
        except (UnicodeDecodeError, OSError, IndexError):
            return self.skip_sample(ind)
        try:
            with Image.open(self.image_files[key]) as img:
                img = random_resized_crop(
                    img, self.image_size, self._rng, self.resize_ratio
                )
                image = image_to_array(img)
        except (OSError, ValueError):
            # corrupt image: substitute another sample (loader.py:83-96)
            return self.skip_sample(ind)
        return tokens, image


class DataLoader:
    """Host-side batcher with per-host sharding and background prefetch.

    Yields dict batches {"text": (b, text_len) int32, "image": (b, h, w, 3)
    float32} ready for one device_put. ``process_index/process_count`` shard
    the sample space across hosts the way the reference's DistributedSampler
    does across ranks (train_dalle.py:391-398).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        prefetch: int = 2,
        collate_fn=None,
    ):
        assert batch_size >= 1
        if collate_fn is not None:
            self._collate = collate_fn
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count
        self.prefetch = prefetch
        self.epoch = 0

    def __len__(self) -> int:
        n = len(self.dataset) // self.process_count
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def _indices(self) -> List[int]:
        idx = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(idx)
        # wrap-pad so every host yields the SAME number of samples/batches —
        # unequal counts would deadlock lockstep collectives at the epoch
        # boundary (torch's DistributedSampler pads the same way)
        per = -(-len(idx) // self.process_count)
        idx = idx + idx[: per * self.process_count - len(idx)]
        return idx[self.process_index :: self.process_count]

    def _produce(self, out_q: queue.Queue):
        try:
            batch: List[Tuple[np.ndarray, np.ndarray]] = []
            for i in self._indices():
                sample = self.dataset[i]
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size:
                    out_q.put(self._collate(batch))
                    batch = []
            if batch and not self.drop_last:
                out_q.put(self._collate(batch))
        finally:
            out_q.put(None)

    @staticmethod
    def _collate(batch):
        text = np.stack([b[0] for b in batch]).astype(np.int32)
        image = np.stack([b[1] for b in batch])
        return {"text": text, "image": image}

    def __iter__(self) -> Iterator[dict]:
        out_q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        worker = threading.Thread(target=self._produce, args=(out_q,), daemon=True)
        worker.start()
        while True:
            item = out_q.get()
            if item is None:
                break
            yield item
        worker.join()
        self.epoch += 1


class ImageFolderDataset:
    """Label-free image folder for VAE training (the reference uses
    torchvision ImageFolder, train_vae.py:107-115; labels were discarded)."""

    def __init__(self, folder: str, image_size: int, seed: int = 0):
        path = Path(folder)
        self.files = sorted(
            p for ext in IMAGE_EXTS for p in path.glob(f"**/*{ext}")
        )
        assert len(self.files) > 0, f"no images found at {folder}"
        self.image_size = image_size
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        return len(self.files)

    def __getitem__(self, ind: int) -> Tuple[np.ndarray, np.ndarray]:
        try:
            with Image.open(self.files[ind]) as img:
                img = random_resized_crop(img, self.image_size, self._rng, 0.75)
                arr = image_to_array(img)
        except (OSError, ValueError):
            return self[(ind + 1) % len(self)]
        return arr, np.zeros((), np.int32)

    @staticmethod
    def collate(batch):
        return {"image": np.stack([b[0] for b in batch])}
