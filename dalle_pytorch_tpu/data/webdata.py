"""Streaming tar-shard pipeline — the webdataset-equivalent.

The reference builds its WebDataset pipeline inline in the trainer
(train_dalle.py:200-216,353-374): brace-expanded ``.tar`` shard lists from
disk, http or GCS (``pipe:curl``/``pipe:gsutil cat``), image/caption members
paired by stem inside each tar, warn-and-continue error handling. This module
re-owns that as a small stdlib implementation: sequential tar streaming
(``r|*`` mode never seeks, so pipes work), per-host shard splitting, a
shuffle buffer, and the same tokenize/crop mapping as the folder loader.
"""

from __future__ import annotations

import io
import random
import re
import shlex
import subprocess
import sys
import tarfile
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np
from PIL import Image

from ..utils.faults import FAULTS
from ..utils.metrics import counters
from ..utils.resilience import RetryPolicy, retry
from ..utils.telemetry import TELEMETRY
from .loader import image_to_array, random_resized_crop

IMAGE_KEYS = ("jpg", "jpeg", "png", "img", "image")
CAPTION_KEYS = ("txt", "caption", "text")

# transient shard-stream failures (flaky GCS/http) retry with backoff;
# DALLE_TPU_SHARD_RETRIES / DALLE_TPU_SHARD_BACKOFF override
SHARD_RETRY = RetryPolicy(attempts=3, base_delay=0.5, retry_on=(OSError,))


def expand_urls(spec: str) -> List[str]:
    """Brace expansion: 'shard-{0000..0003}.tar' -> 4 urls (the webdataset
    convention the reference relies on, train_dalle.py:200-216)."""
    m = re.search(r"\{(\d+)\.\.(\d+)\}", spec)
    if not m:
        return [spec]
    lo, hi = m.group(1), m.group(2)
    width = len(lo)
    out = []
    for i in range(int(lo), int(hi) + 1):
        out.extend(expand_urls(spec[: m.start()] + str(i).zfill(width) + spec[m.end() :]))
    return out


class _PipeStream:
    """Wraps a pipe: subprocess stdout; close() reaps the child and surfaces
    a nonzero exit so a dead curl isn't mistaken for a short shard."""

    def __init__(self, cmd: str):
        self._proc = subprocess.Popen(
            shlex.split(cmd), stdout=subprocess.PIPE, stderr=subprocess.PIPE
        )
        self._cmd = cmd

    def read(self, *a):
        return self._proc.stdout.read(*a)

    def close(self):
        self._proc.stdout.close()
        err = self._proc.stderr.read().decode(errors="replace")
        self._proc.stderr.close()
        code = self._proc.wait()
        if code != 0:
            print(
                f"pipe command failed (exit {code}): {self._cmd}\n{err[-500:]}",
                file=sys.stderr,
            )


def open_shard(url: str):
    """A binary stream for one shard: local path, or 'pipe:<command>'
    (curl/gsutil streaming, reference train_dalle.py:205-211)."""
    if url.startswith("pipe:"):
        return _PipeStream(url[len("pipe:") :])
    return open(url, "rb")


def iter_tar_samples(stream) -> Iterator[Dict[str, bytes]]:
    """Group tar members by stem into {extension: bytes} sample dicts.
    Members are assumed stem-contiguous (the webdataset layout)."""
    current_stem: Optional[str] = None
    sample: Dict[str, bytes] = {}
    with tarfile.open(fileobj=stream, mode="r|*") as tf:
        for member in tf:
            if not member.isfile():
                continue
            name = Path(member.name)
            stem, ext = str(name.parent / name.stem), name.suffix.lstrip(".").lower()
            if stem != current_stem:
                if sample:
                    yield sample
                current_stem, sample = stem, {}
            f = tf.extractfile(member)
            if f is not None:
                sample[ext] = f.read()
    if sample:
        yield sample


class TarImageTextDataset:
    """Iterable (tokens, image) stream over tar shards.

    Warn-and-continue on malformed samples (the reference's
    wds.warn_and_continue, train_dalle.py:372) — but counted, never silent:
    every drop lands in ``utils.metrics.counters`` under ``webdata.*``
    (decode errors, shard opens/aborts, quarantines). A shard whose open
    keeps failing after retries is QUARANTINED — skipped for the rest of
    this dataset's life instead of re-hammering a dead URL every epoch.
    """

    def __init__(
        self,
        urls: str,
        text_len: int = 256,
        image_size: int = 128,
        truncate_captions: bool = False,
        resize_ratio: float = 0.75,
        tokenizer=None,
        image_key: Optional[str] = None,
        caption_key: Optional[str] = None,
        shuffle_buffer: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        seed: int = 0,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.retry_policy = (retry_policy or SHARD_RETRY).from_env(
            "DALLE_TPU_SHARD"
        )
        self._quarantined: set = set()
        self.urls = expand_urls(urls)
        assert self.urls, f"no shards matched {urls}"
        self.text_len = text_len
        self.image_size = image_size
        self.truncate_captions = truncate_captions
        self.resize_ratio = resize_ratio
        if tokenizer is None:
            from .tokenizers import get_tokenizer

            tokenizer = get_tokenizer()
        self.tokenizer = tokenizer
        self.image_keys = (image_key,) if image_key else IMAGE_KEYS
        self.caption_keys = (caption_key,) if caption_key else CAPTION_KEYS
        self.shuffle_buffer = shuffle_buffer
        self.process_index = process_index
        self.process_count = process_count
        self._rng = random.Random(seed + process_index)

    def _my_shards(self) -> List[str]:
        # wrap-pad so no host ends up with zero shards (sample counts can
        # still differ per shard — tar streams carry no epoch barrier)
        urls = list(self.urls)
        if len(urls) % self.process_count:
            urls = urls + urls[: self.process_count - len(urls) % self.process_count]
        return urls[self.process_index :: self.process_count]

    def _map(self, sample: Dict[str, bytes]) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        img_bytes = next(
            (sample[k] for k in self.image_keys if k in sample), None
        )
        cap_bytes = next(
            (sample[k] for k in self.caption_keys if k in sample), None
        )
        if img_bytes is None or cap_bytes is None:
            return None
        try:
            caption = cap_bytes.decode("utf-8")
            tokens = self.tokenizer.tokenize(
                caption, self.text_len, truncate_text=self.truncate_captions
            )[0]
            with Image.open(io.BytesIO(img_bytes)) as img:
                img = random_resized_crop(
                    img, self.image_size, self._rng, self.resize_ratio
                )
                image = image_to_array(img)
        except Exception as e:  # warn-and-continue, but accounted
            counters.inc("webdata.decode_errors")
            print(f"tar sample skipped: {type(e).__name__}: {e}", file=sys.stderr)
            return None
        return tokens, image

    def _open_with_retry(self, url: str):
        """Open one shard, retrying transient failures; -> stream or None
        (after quarantining). The ``shard_open`` fault site injects the
        failures tests use to pin both paths."""

        def attempt():
            FAULTS.maybe_raise("shard_open", OSError("injected shard_open fault"))
            return open_shard(url)

        try:
            stream = retry(
                attempt,
                self.retry_policy,
                describe=f"open shard {url}",
                on_retry=lambda i, e: counters.inc("webdata.shard_open_retries"),
            )
        except self.retry_policy.retry_on as e:
            self._quarantined.add(url)
            counters.inc("webdata.shards_quarantined")
            # flight-recorder events carry the counter name they increment
            # so a postmortem trace joins against the metric series
            TELEMETRY.event(
                "data.shard_quarantined", url=url,
                counter="webdata.shards_quarantined", error=str(e),
            )
            print(
                f"shard {url} quarantined after "
                f"{self.retry_policy.attempts} attempts: {e}",
                file=sys.stderr,
            )
            return None
        counters.inc("webdata.shards_opened")
        TELEMETRY.event(
            "data.shard_open", url=url, counter="webdata.shards_opened",
        )
        return stream

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        buf: List[Tuple[np.ndarray, np.ndarray]] = []
        shards = list(self._my_shards())
        if self.shuffle_buffer:
            self._rng.shuffle(shards)
        for url in shards:
            if url in self._quarantined:
                counters.inc("webdata.quarantined_skips")
                continue
            stream = self._open_with_retry(url)
            if stream is None:
                continue
            try:
                for raw in iter_tar_samples(stream):
                    FAULTS.maybe_raise(
                        "shard_read", tarfile.TarError("injected shard_read fault")
                    )
                    mapped = self._map(raw)
                    if mapped is None:
                        continue
                    if self.shuffle_buffer:
                        buf.append(mapped)
                        if len(buf) >= self.shuffle_buffer:
                            i = self._rng.randrange(len(buf))
                            buf[i], buf[-1] = buf[-1], buf[i]
                            yield buf.pop()
                    else:
                        yield mapped
            except tarfile.TarError as e:
                # mid-shard corruption/truncation: keep what streamed,
                # move on to the next shard — counted, not silent
                counters.inc("webdata.shard_aborts")
                TELEMETRY.event(
                    "data.shard_abort", url=url,
                    counter="webdata.shard_aborts", error=str(e),
                )
                print(f"shard {url} aborted: {e}", file=sys.stderr)
            finally:
                stream.close()
        self._rng.shuffle(buf)
        yield from buf


class TarLoader:
    """Batch iterator over a TarImageTextDataset (the reference's WebLoader
    role, train_dalle.py:400-405)."""

    def __init__(self, dataset: TarImageTextDataset, batch_size: int):
        self.dataset = dataset
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[dict]:
        batch: List[Tuple[np.ndarray, np.ndarray]] = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield {
                    "text": np.stack([b[0] for b in batch]).astype(np.int32),
                    "image": np.stack([b[1] for b in batch]),
                }
                batch = []
