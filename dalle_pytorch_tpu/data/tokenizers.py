"""Tokenizers, TPU-native data layer.

Re-owns the reference's four interchangeable tokenizers (tokenizer.py:20-266)
behind one duck-type: ``encode(text) -> [int]``, ``decode(ids, pad_tokens=...)
-> str``, ``vocab_size``, and ``tokenize(texts, context_length, truncate_text)
-> (b, context_length) int32 numpy array`` with the exact 0-pad / raise-unless-
truncate contract (tokenizer.py:137-152). Outputs are host numpy — the device
boundary is crossed once per batch by the loader, not per sample.

``SimpleTokenizer`` follows OpenAI's MIT-licensed CLIP byte-level BPE (byte ->
unicode remap, end-of-word ``</w>`` marker, rank-greedy merge loop) over the
standard ``bpe_simple_vocab_16e6.txt`` merges file (vocab 49408), which is
vendored as package data (like the reference's MANIFEST.in) with env-var and
cache-dir overrides.

ftfy is optional (reference hard-requires it, tokenizer.py:4): when absent,
a NFC-normalization fallback keeps behavior sane on clean corpora.
"""

from __future__ import annotations

import html
import os
import unicodedata
from functools import lru_cache
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

try:
    import ftfy

    _HAS_FTFY = True
except ImportError:
    _HAS_FTFY = False

import regex as re

_BPE_FILENAME = "bpe_simple_vocab_16e6.txt"


def default_bpe_path() -> Optional[str]:
    """Locate the standard CLIP BPE merges file. The vocab is vendored with
    the package (like the reference's MANIFEST.in:1 shipping
    dalle_pytorch/data/bpe_simple_vocab_16e6.txt), so the package-relative
    path always resolves for a normal install/checkout."""
    candidates = [
        os.environ.get("DALLE_TPU_BPE_PATH"),
        str(Path(__file__).parent / _BPE_FILENAME),
        str(Path.home() / ".cache" / "dalle_tpu" / _BPE_FILENAME),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return c
    return None


@lru_cache()
def bytes_to_unicode():
    """Reversible byte -> printable-unicode map (the GPT-2/CLIP trick that
    keeps BPE free of unk tokens while avoiding raw control characters)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def basic_clean(text: str) -> str:
    if _HAS_FTFY:
        text = ftfy.fix_text(text)
    else:
        text = unicodedata.normalize("NFC", text)
    text = html.unescape(html.unescape(text))
    return text.strip()


def whitespace_clean(text: str) -> str:
    return re.sub(r"\s+", " ", text).strip()


def _pairs(word: Sequence[str]):
    return set(zip(word[:-1], word[1:]))


class _TokenizeMixin:
    """The shared tokenize() contract (reference tokenizer.py:137-152)."""

    def tokenize(
        self,
        texts: Union[str, Iterable[str]],
        context_length: int = 256,
        truncate_text: bool = False,
    ) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        all_tokens = [self.encode(t) for t in texts]
        out = np.zeros((len(all_tokens), context_length), dtype=np.int32)
        for i, tokens in enumerate(all_tokens):
            if len(tokens) > context_length:
                if truncate_text:
                    tokens = tokens[:context_length]
                else:
                    raise RuntimeError(
                        f"Input {texts[i]} is too long for context length "
                        f"{context_length}"
                    )
            out[i, : len(tokens)] = tokens
        return out


class SimpleTokenizer(_TokenizeMixin):
    """Byte-level BPE over the bundled 16e6 merges vocabulary (49408 tokens),
    drop-in for the reference's SimpleTokenizer (tokenizer.py:20-154).

    Algorithm ancestry: this follows OpenAI's MIT-licensed CLIP tokenizer
    (which the reference vendors verbatim) — byte-exact vocab compatibility
    pins the merges slicing, vocab assembly order, regex pattern, and the
    greedy lowest-rank merge loop, so the implementation necessarily mirrors
    that public code rather than being an independent design."""

    def __init__(self, bpe_path: Optional[str] = None):
        bpe_path = bpe_path or default_bpe_path()
        if bpe_path is None:
            raise FileNotFoundError(
                f"{_BPE_FILENAME} not found; set DALLE_TPU_BPE_PATH or place "
                f"it in ~/.cache/dalle_tpu/"
            )
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}

        merges = Path(bpe_path).read_text(encoding="utf8").split("\n")
        merges = merges[1 : 49152 - 256 - 2 + 1]
        merges = [tuple(m.split()) for m in merges]

        vocab = list(bytes_to_unicode().values())
        vocab = vocab + [v + "</w>" for v in vocab]
        for merge in merges:
            vocab.append("".join(merge))
        vocab.extend(["<|startoftext|>", "<|endoftext|>"])

        self.encoder = dict(zip(vocab, range(len(vocab))))
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.cache = {
            "<|startoftext|>": "<|startoftext|>",
            "<|endoftext|>": "<|endoftext|>",
        }
        self.pat = re.compile(
            r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|"
            r"[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+",
            re.IGNORECASE,
        )
        self.vocab_size = len(self.encoder)  # 49408

    def bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = _pairs(word)
        if not pairs:
            return token + "</w>"

        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i] == first and word[i + 1] == second:
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _pairs(word)
        result = " ".join(word)
        self.cache[token] = result
        return result

    def encode(self, text: str) -> List[int]:
        bpe_tokens: List[int] = []
        text = whitespace_clean(basic_clean(text)).lower()
        for token in re.findall(self.pat, text):
            token = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            bpe_tokens.extend(self.encoder[t] for t in self.bpe(token).split(" "))
        return bpe_tokens

    def decode(self, tokens: Iterable[int], pad_tokens: set = frozenset()) -> str:
        """ids -> text; ``pad_tokens`` (e.g. DALLE's per-position padding ids)
        are dropped, as are 0s (the shared pad id)."""
        text = "".join(
            self.decoder[int(t)]
            for t in tokens
            if int(t) not in pad_tokens and int(t) != 0
        )
        return (
            bytearray(self.byte_decoder[c] for c in text)
            .decode("utf-8", errors="replace")
            .replace("</w>", " ")
        )


class HugTokenizer(_TokenizeMixin):
    """Custom byte-level BPE from a HuggingFace ``tokenizers`` json file
    (reference tokenizer.py:158-192)."""

    def __init__(self, bpe_path: str):
        from tokenizers import Tokenizer  # Rust engine, baked in

        assert Path(bpe_path).exists(), f"BPE json path {bpe_path} does not exist"
        self.tokenizer = Tokenizer.from_file(str(bpe_path))
        self.vocab_size = self.tokenizer.get_vocab_size()

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text).ids

    def decode(self, tokens: Iterable[int], pad_tokens: set = frozenset()) -> str:
        ids = [int(t) for t in tokens if int(t) not in pad_tokens and int(t) != 0]
        return self.tokenizer.decode(ids, skip_special_tokens=True)


class ChineseTokenizer(_TokenizeMixin):
    """BERT WordPiece for Chinese (reference tokenizer.py:196-228)."""

    def __init__(self, model_name: str = "bert-base-chinese"):
        from transformers import BertTokenizer

        self.tokenizer = BertTokenizer.from_pretrained(model_name)
        self.vocab_size = self.tokenizer.vocab_size

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=False)

    def decode(self, tokens: Iterable[int], pad_tokens: set = frozenset()) -> str:
        ids = [int(t) for t in tokens if int(t) not in pad_tokens and int(t) != 0]
        return self.tokenizer.decode(ids)


class YttmTokenizer(_TokenizeMixin):
    """youtokentome BPE (reference tokenizer.py:232-266). The C++ yttm wheel
    is not part of this image; the class gates on import so the API surface
    stays complete."""

    def __init__(self, bpe_path: str):
        assert Path(bpe_path).exists(), f"BPE model path {bpe_path} does not exist"
        try:
            import youtokentome as yttm
        except ImportError as e:
            raise ImportError(
                "YttmTokenizer requires the youtokentome package"
            ) from e
        self.tokenizer = yttm.BPE(model=str(bpe_path))
        self.vocab_size = self.tokenizer.vocab_size()

    def encode(self, text: str) -> List[int]:
        import youtokentome as yttm

        return self.tokenizer.encode([text], output_type=yttm.OutputType.ID)[0]

    def decode(self, tokens: Iterable[int], pad_tokens: set = frozenset()) -> str:
        return self.tokenizer.decode(
            [[int(t) for t in tokens]], ignore_ids=list(pad_tokens) + [0]
        )[0]


_default: Optional[_TokenizeMixin] = None


def get_tokenizer() -> _TokenizeMixin:
    """Lazily-built module default (the reference builds one at import,
    tokenizer.py:154; lazy keeps import cheap when the vocab is elsewhere).

    Prefers the native C++ engine (native/bpe_tokenizer.cc, byte-exact with
    SimpleTokenizer — tests/test_native_bpe.py); set DALLE_TPU_NO_NATIVE=1 to
    force the pure-Python implementation."""
    global _default
    if _default is None:
        if os.environ.get("DALLE_TPU_NO_NATIVE", "") in ("", "0"):
            try:
                from .native_bpe import NativeSimpleTokenizer

                _default = NativeSimpleTokenizer()
            except Exception as e:
                import warnings

                warnings.warn(
                    f"native BPE engine unavailable ({e!r}); falling back to "
                    f"the pure-Python tokenizer (slower). Set "
                    f"DALLE_TPU_NO_NATIVE=1 to silence this."
                )
                _default = None
        if _default is None:
            _default = SimpleTokenizer()
    return _default
