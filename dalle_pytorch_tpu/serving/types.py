"""Serving request/response vocabulary: the typed surface of the
continuous-batching engine.

Every request submitted to the engine ends in exactly ONE
``RequestResult`` whose ``outcome`` is a member of ``Outcome`` — there is
no code path that drops a request silently (the acceptance invariant the
engine tests pin: outcome counters sum to submissions). Overload and
failure are *values* here, not exceptions: a rejected request is a result
with a ``RejectReason``, a missed deadline is a result, a request evicted
past the preemption cap is a result. The only exceptions the engine
raises are programmer errors (unsupported model, bad config).

The clock is injectable (``Clock`` / ``FakeClock``) so every time-driven
behavior — deadlines, queue aging, latency accounting, the
``decode_stall`` fault — is deterministic in CPU tests: the engine calls
``tick()`` once per scheduling iteration, which a ``FakeClock`` turns
into a fixed virtual step cost and the real clock ignores.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from enum import Enum
from typing import Optional

import numpy as np


class Outcome(str, Enum):
    """Terminal state of a submitted request. str-valued so results
    serialize into bench/smoke JSON without a custom encoder."""

    COMPLETED = "completed"
    REJECTED = "rejected"
    DEADLINE_EXCEEDED = "deadline_exceeded"
    CANCELLED = "cancelled"
    PREEMPT_CAP = "preempt_cap"
    PREFILL_FAILED = "prefill_failed"
    # Typed-DEGRADED completions from the post-decode pipeline
    # (serving/postdecode.py, DESIGN.md §8.5): the token work succeeded
    # but a post-decode stage was shed — by retry exhaustion, backlog, or
    # fleet pressure past the stage watermark. Tokens (and, for UNRANKED,
    # the decoded image) are complete and bit-exact; only the shed stage's
    # value is missing. These are successes of the degradation policy,
    # not failures.
    COMPLETED_TOKENS_ONLY = "completed_tokens_only"  # image never decoded
    COMPLETED_UNRANKED = "completed_unranked"        # image, no CLIP score


class RejectReason(str, Enum):
    DEMAND_EXCEEDS_POOL = "demand_exceeds_pool"  # can never fit, even idle
    QUEUE_FULL = "queue_full"                    # bounded admission queue
    # router-level (serving/router.py): the fleet has no live replica left
    # to run anything — every replica is DEAD/retired. Queued requests are
    # flushed with this reason rather than hanging forever.
    NO_REPLICA = "no_replica"


@dataclass(frozen=True)
class Request:
    """One generation request.

    ``prompt`` is the RAW text-token row ((text_seq_len,) int, 0-padded —
    tokenizer output; the engine remaps/boses it). ``deadline`` is an
    absolute time on the engine's clock; None = no deadline. ``priority``:
    higher runs first and is evicted last. ``seed`` keys the request's
    private sampling stream: token at internal position p is drawn with
    ``fold_in(key(seed), p)``, which is what makes a preempted-and-replayed
    request reproduce its tokens bit-identically."""

    request_id: str
    prompt: np.ndarray
    max_new_tokens: int
    deadline: Optional[float] = None
    priority: int = 0
    seed: int = 0


@dataclass
class RequestResult:
    request_id: str
    outcome: Outcome
    # generated image-token ids; complete for COMPLETED, the partial prefix
    # for deadline/cancel/preempt-cap terminations (callers decide whether
    # partials are useful), None for requests that never prefilled
    tokens: Optional[np.ndarray] = None
    reject_reason: Optional[RejectReason] = None
    preempt_count: int = 0
    prefill_attempts: int = 0
    # set when watermark degradation clamped the request's budget; the
    # response CARRIES the clamp instead of silently under-generating
    clamped_max_new_tokens: Optional[int] = None
    queue_latency_s: Optional[float] = None
    # time-to-first-token: submit -> the first image token first sampled
    # (at prefill completion). Set once; a preempted-and-replayed request
    # keeps its ORIGINAL ttft (replay regenerates the same token), and a
    # request that never finished a prefill reports None.
    ttft_s: Optional[float] = None
    total_latency_s: Optional[float] = None
    # server-provided backoff hint on load-typed rejections (QUEUE_FULL /
    # NO_REPLICA): how long the submitter should wait before retrying,
    # derived from fleet occupancy and the respawn ladder. None on every
    # other outcome — DEMAND_EXCEEDS_POOL is permanent, retrying is futile.
    retry_after_s: Optional[float] = None
    # post-decode pipeline results (serving/postdecode.py): the decoded
    # image (H, W, C float32, VAE-normalized space — denormalize() to
    # display) and the CLIP rerank score. image is set on COMPLETED and
    # COMPLETED_UNRANKED (and on mid-stage cancel/deadline partials when
    # VAE had finished); rerank_score only on fully-COMPLETED reranked
    # requests. Both None when the engine runs without stages.
    image: Optional[np.ndarray] = None
    rerank_score: Optional[float] = None
    detail: str = ""

    def to_json(self) -> dict:
        return {
            "request_id": self.request_id,
            "outcome": self.outcome.value,
            "n_tokens": None if self.tokens is None else int(len(self.tokens)),
            # the image itself stays out of JSON; shape documents presence
            "image_shape": None if self.image is None else list(self.image.shape),
            "rerank_score": self.rerank_score,
            "reject_reason": (
                None if self.reject_reason is None else self.reject_reason.value
            ),
            "preempt_count": self.preempt_count,
            "prefill_attempts": self.prefill_attempts,
            "clamped_max_new_tokens": self.clamped_max_new_tokens,
            "queue_latency_s": self.queue_latency_s,
            "ttft_s": self.ttft_s,
            "total_latency_s": self.total_latency_s,
            "retry_after_s": self.retry_after_s,
            "detail": self.detail,
        }


# ------------------------------------------------------------------ clock


class Clock:
    """Engine time source. ``now()`` is an absolute monotonic time;
    ``tick()`` is called once per engine scheduling iteration (a seam, not
    a timer); ``advance(dt)`` jumps time forward — the ``decode_stall``
    fault drives it."""

    def now(self) -> float:
        return time.monotonic()

    def tick(self) -> None:
        pass

    def advance(self, dt: float) -> None:
        # real time cannot be jumped; a stall on the real clock is a sleep
        time.sleep(dt)


@dataclass
class FakeClock(Clock):
    """Deterministic virtual clock: every engine iteration costs a fixed
    ``step_dt`` (so "a deadline mid-decode" is an exact step count in
    tests) and ``advance`` jumps instantly."""

    t: float = 0.0
    step_dt: float = 0.0

    def now(self) -> float:
        return self.t

    def tick(self) -> None:
        self.t += self.step_dt

    def advance(self, dt: float) -> None:
        self.t += dt


class EngineUnsupportedModel(ValueError):
    """The model cannot run under the continuous-batching engine (gMLP
    layers: the spatial-gate history indexes by a scalar absolute position,
    so per-slot ragged offsets cannot be expressed — same restriction as
    ``merge_decode_caches``/``set_decode_offsets``)."""
