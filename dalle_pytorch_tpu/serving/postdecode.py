"""Post-decode request stages: VAE decode + CLIP rerank inside the engine.

A request whose image tokens have completed does not leave the serving
layer yet — it transitions through typed post-decode stages
(docs/DESIGN.md §8.5)::

    tokens complete -> VAE_DECODE -> [CLIP_RERANK] -> DONE

with the same robustness contract the token path already carries:

- **Subordinate to decode.** Stage work is metered by a per-iteration
  stage budget that literally reuses :class:`~.scheduler.TokenBudget`
  (``chunk=1``, budget in images): per engine iteration at most
  ``budget`` staged images are dispatched, in at most one fixed-width
  batched jit per stage, so the max decode-iteration gap stays within
  the chunked-prefill interference bound — stage work can never stall
  token decode for longer than one bounded stage dispatch.
- **Typed faults + retry.** Each dispatch passes the fault sites
  ``vae_decode_fail`` / ``rerank_fail`` / ``stage_timeout``
  (utils/faults.py) and a real-elapsed timeout; a failed attempt backs
  the item off by ``RetryPolicy.delay`` (deterministic — no rng — so
  chaos replays are bit-reproducible).
- **Graceful degradation, never unbounded queueing.** Retry exhaustion,
  a full stage backlog, or fleet occupancy past the watermark completes
  the request **typed-degraded** instead of stalling it:
  ``COMPLETED_TOKENS_ONLY`` (no image yet) or ``COMPLETED_UNRANKED``
  (image decoded, rerank skipped). Degradation is an outcome value, not
  an exception — exactly the overload philosophy of the token path.
- **Crash-replayable stage boundaries.** The ``on_stage`` hook fires at
  every completed boundary (tokens -> pipeline, VAE -> image) with the
  payload needed to resume; the router journals it
  (``{"kind": "stage", ...}`` records, serving/journal.py) so a crash
  mid-VAE or mid-rerank replays idempotently from the last completed
  stage with bit-identical completed results.
- **Fixed-shape stage jits.** ``serving.vae_decode`` and
  ``serving.clip_rerank`` are batched fixed-width jits registered in
  the trace contracts (tools/trace_contracts.json) under the standing
  zero-in-trace-compile and donation budgets; partial batches are
  padded host-side by repeating the tail row so one signature serves
  every occupancy.

Stretch hooks: ``stream_preview`` emits progressive partial results at
each stage boundary, and staged work dispatches in ``(-priority, seq)``
order so a low-priority offline lane (negative-priority requests via
the existing priority machinery) naturally yields stage capacity to
interactive traffic.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.faults import FAULTS
from ..utils.resilience import RetryPolicy
from ..utils.telemetry import TELEMETRY
from .scheduler import Entry, TokenBudget
from .types import Outcome

# Stage names — journal record vocabulary (serving/journal.py) and the
# state-machine states of DESIGN.md §8.5. STAGE_TOKENS marks the
# tokens-complete boundary (entry INTO the pipeline), not a queue.
STAGE_TOKENS = "tokens"
STAGE_VAE = "vae_decode"
STAGE_RERANK = "clip_rerank"


# --------------------------------------------------------------- stage jits
#
# Module-level like the engine's own jits: the flax module is a static
# (hashable) argument, so every engine sharing a module/config shares one
# compiled executable per shape signature. Contract entries
# serving.vae_decode / serving.clip_rerank (tools/lint/trace/registry.py)
# pin the canonical signatures; no donation (inputs are host-built batches
# reused nowhere else — donating would not save a buffer that matters).


@partial(jax.jit, static_argnums=(0,))
def _vae_decode_jit(vae, params, img_seq):
    """Token ids (S, n) -> pixels (S, H, W, C) via the VAE decoder."""
    return vae.apply({"params": params}, img_seq, method="decode")


@partial(jax.jit, static_argnums=(0,))
def _clip_rerank_jit(clip, params, text, images):
    """Per-pair CLIP similarity (S,) for (S, L) text ids and (S, H, W, C)
    pixels; resize to the CLIP visual resolution happens in-trace so the
    stage is one dispatch regardless of the VAE's output size."""
    n = images.shape[0]
    imgs = jax.image.resize(
        images,
        (n, clip.visual_image_size, clip.visual_image_size, images.shape[-1]),
        method="bilinear",
    )
    return clip.apply({"params": params}, text, imgs, text_mask=text != 0)


@dataclass(frozen=True)
class StageConfig:
    """Operator knobs for the post-decode pipeline. Defaults are
    permissive (watermark 1.0 = occupancy-triggered degradation off;
    occupancy is <= 1.0 so only an explicit watermark < 1.0 arms it);
    the backlog cap still bounds queueing unconditionally."""

    batch: int = 2                  # fixed jit batch width per stage
    budget: Optional[int] = None    # images/iteration (TokenBudget); None -> batch
    queue_limit: int = 64           # staged backlog cap -> degrade at entry
    high_watermark: float = 1.0     # fleet occupancy past this -> degrade at entry
    retry: RetryPolicy = RetryPolicy(
        attempts=3, base_delay=0.25, max_delay=2.0, jitter=0.0, retry_on=())
    timeout_s: float = 30.0         # real-elapsed per-dispatch bound
    rerank: bool = True             # run CLIP_RERANK when a CLIP is supplied

    def __post_init__(self):
        assert self.batch >= 1, self.batch
        assert self.budget is None or self.budget >= 1, self.budget
        assert self.queue_limit >= 1, self.queue_limit
        assert self.retry.attempts >= 1, self.retry.attempts


@dataclass(frozen=True)
class StageSpec:
    """The models the pipeline runs: a DiscreteVAE (required) and an
    optional CLIP; ``Engine(..., stages=StageSpec(...))`` enables the
    pipeline. ``clip=None`` (or ``config.rerank=False``) skips the
    rerank stage — requests complete with an unscored image."""

    vae: object
    vae_params: object
    clip: Optional[object] = None
    clip_params: Optional[object] = None
    config: StageConfig = StageConfig()


@dataclass
class _Staged:
    """One request parked in the pipeline (holds NO kv pages — the slot
    and its pages were released when tokens completed)."""

    entry: Entry
    tokens: np.ndarray              # completed image tokens (int32)
    stage: str                      # STAGE_VAE | STAGE_RERANK
    image: Optional[np.ndarray] = None
    attempts: int = 0               # failures at the CURRENT stage
    ready_at: float = 0.0           # clock time the next attempt may run


class PostDecodePipeline:
    """Host-side stage queue + batched dispatch. Owned by an Engine and
    driven from ``Engine.step()`` (so, behind a Router, always under the
    router lock — the ``on_stage`` journal hook needs no locking of its
    own)."""

    def __init__(self, spec: StageSpec, *, clock, counters, gauges,
                 histograms, finish: Callable, occupancy=None):
        if spec.vae is None:
            raise ValueError("StageSpec.vae is required")
        if spec.clip is not None and spec.clip_params is None:
            raise ValueError("StageSpec.clip without clip_params")
        self.spec = spec
        self.cfg = spec.config
        self.rerank = bool(self.cfg.rerank and spec.clip is not None)
        self._clock = clock
        self.counters = counters
        self.gauges = gauges
        self.histograms = histograms
        # finish(entry, outcome, tokens, image=, score=, detail=) — the
        # engine's _finish_staged; every staged request ends through it.
        self._finish = finish
        self._occupancy = occupancy
        self._budget = TokenBudget(
            budget=self.cfg.budget if self.cfg.budget is not None
            else self.cfg.batch,
            chunk=1,
        )
        self._staged: List[_Staged] = []
        # Stage-boundary hook: on_stage(request_id, stage, payload) with
        # payload {"tokens": [ids]} or {"image": np.ndarray}. The router
        # binds this to its journal (crash replay) and failover state.
        self.on_stage: Optional[Callable[[str, str, dict], None]] = None
        # Stretch: progressive partial results —
        # stream_preview(request_id, stage, value) per completed boundary.
        self.stream_preview: Optional[Callable[[str, str, object], None]] = None

    # ------------------------------------------------------------- introspection

    def __len__(self) -> int:
        return len(self._staged)

    def __bool__(self) -> bool:
        return bool(self._staged)

    def ids(self) -> List[str]:
        return [s.entry.request.request_id for s in self._staged]

    # ------------------------------------------------------------------- entry

    def enqueue(self, entry: Entry, tokens: np.ndarray,
                image: Optional[np.ndarray] = None,
                announce: bool = True) -> None:
        """Park a tokens-complete request in the pipeline.

        ``image`` resumes at CLIP_RERANK (journal replay / failover from
        a vae_decode stage record); ``announce=False`` suppresses the
        ``on_stage`` boundary hook for exactly those resume paths, whose
        records are already durable."""
        rid = entry.request.request_id
        tokens = np.asarray(tokens, np.int32)
        now = self._clock.now()
        self.counters.inc("serve.stage.enqueued")
        if announce and self.on_stage is not None:
            self.on_stage(rid, STAGE_TOKENS, {"tokens": [int(t) for t in tokens]})
        st = _Staged(entry=entry, tokens=tokens,
                     stage=STAGE_RERANK if image is not None else STAGE_VAE,
                     image=image, ready_at=now)
        # Pressure degradation at the stage boundary: past-saturation
        # requests complete typed-degraded instead of queueing unboundedly.
        occ = self._occupancy() if self._occupancy is not None else 0.0
        if len(self._staged) >= self.cfg.queue_limit:
            self._degrade(st, "stage_backlog")
            return
        if occ > self.cfg.high_watermark:
            self._degrade(st, "stage_watermark")
            return
        if st.stage == STAGE_RERANK and not self.rerank:
            # resumed past VAE but rerank is off: already fully complete
            self._complete(st, score=None, now=now)
            return
        self._staged.append(st)

    # ------------------------------------------------------------------ sweeps

    def sweep(self, cancelled_ids, now: float) -> List[str]:
        """Terminate staged requests that were cancelled or whose deadline
        passed (same semantics as a running row: the typed outcome carries
        the partial results — tokens always, the image if VAE finished).
        Returns the request ids of cancelled entries."""
        hit = []
        for st in list(self._staged):
            rid = st.entry.request.request_id
            ddl = st.entry.request.deadline
            if rid in cancelled_ids:
                self._staged.remove(st)
                self._finish(st.entry, Outcome.CANCELLED, st.tokens,
                             image=st.image, detail=f"cancelled in {st.stage}")
                hit.append(rid)
            elif ddl is not None and now > ddl:
                self._staged.remove(st)
                self._finish(st.entry, Outcome.DEADLINE_EXCEEDED, st.tokens,
                             image=st.image, detail=f"deadline in {st.stage}")
        return hit

    # ---------------------------------------------------------------- dispatch

    def step(self) -> bool:
        """One iteration of stage work, budgeted. Rerank is head-of-line
        (draining the furthest-along work frees pipeline capacity
        fastest); within a stage, dispatch order is (-priority, seq) —
        the offline lane yields to interactive requests."""
        if not self._staged:
            return False
        now = self._clock.now()
        order = sorted(self._staged,
                       key=lambda s: (-s.entry.request.priority, s.entry.seq))
        ready_rr = [s for s in order
                    if s.stage == STAGE_RERANK and s.ready_at <= now]
        ready_vae = [s for s in order
                     if s.stage == STAGE_VAE and s.ready_at <= now]
        grants = self._budget.plan(0, [len(ready_rr), len(ready_vae)])
        worked = False
        if grants[0]:
            worked = self._dispatch(
                STAGE_RERANK, ready_rr[:min(grants[0], self.cfg.batch)], now
            ) or worked
        if grants[1]:
            worked = self._dispatch(
                STAGE_VAE, ready_vae[:min(grants[1], self.cfg.batch)], now
            ) or worked
        return worked

    def _dispatch(self, stage: str, batch: List[_Staged], now: float) -> bool:
        if not batch:
            return False
        if stage == STAGE_VAE:
            site, fired = "vae_decode_fail", FAULTS.take("vae_decode_fail")
        else:
            site, fired = "rerank_fail", FAULTS.take("rerank_fail")
        if fired:
            self.counters.inc(f"serve.fault_{site}")
            self._retry_or_degrade(batch, now, site)
            return True
        if FAULTS.take("stage_timeout"):
            self.counters.inc("serve.fault_stage_timeout")
            self.counters.inc("serve.stage.timeouts")
            self._retry_or_degrade(batch, now, "stage_timeout")
            return True
        t0 = time.monotonic()
        span = ("serve.stage.vae_decode" if stage == STAGE_VAE
                else "serve.stage.clip_rerank")
        with TELEMETRY.span(span, n=len(batch)):
            if stage == STAGE_VAE:
                out = np.asarray(_vae_decode_jit(
                    self.spec.vae, self.spec.vae_params,
                    jnp.asarray(self._pad(np.stack([s.tokens for s in batch])))))
            else:
                texts = np.stack([self._clip_text(s.entry.request) for s in batch])
                images = np.stack([s.image for s in batch])
                out = np.asarray(_clip_rerank_jit(
                    self.spec.clip, self.spec.clip_params,
                    jnp.asarray(self._pad(texts)),
                    jnp.asarray(self._pad(images))))
        if time.monotonic() - t0 > self.cfg.timeout_s:
            self.counters.inc("serve.stage.timeouts")
            self._retry_or_degrade(batch, now, "stage_timeout")
            return True
        for i, st in enumerate(batch):
            st.attempts = 0
            rid = st.entry.request.request_id
            if stage == STAGE_VAE:
                st.image = np.asarray(out[i], np.float32)
                self.counters.inc("serve.stage.vae_images")
                if self.on_stage is not None:
                    self.on_stage(rid, STAGE_VAE, {"image": st.image})
                if self.stream_preview is not None:
                    self.stream_preview(rid, STAGE_VAE, st.image)
                if self.rerank:
                    st.stage = STAGE_RERANK
                    st.ready_at = now
                else:
                    self._staged.remove(st)
                    self._complete(st, score=None, now=now)
            else:
                self.counters.inc("serve.stage.reranked")
                score = float(out[i])
                if self.stream_preview is not None:
                    self.stream_preview(rid, STAGE_RERANK, score)
                self._staged.remove(st)
                self._complete(st, score=score, now=now)
        return True

    def warmup(self) -> None:
        """Pay both stage-jit compiles at the canonical batch width (the
        bench's zero-in-trace-compile window assumes this ran)."""
        n = self.spec.vae.image_seq_len
        seqs = jnp.zeros((self.cfg.batch, n), jnp.int32)
        imgs = _vae_decode_jit(self.spec.vae, self.spec.vae_params, seqs)
        if self.rerank:
            texts = jnp.zeros((self.cfg.batch, self.spec.clip.text_seq_len),
                              jnp.int32)
            _clip_rerank_jit(self.spec.clip, self.spec.clip_params,
                             texts, imgs).block_until_ready()
        else:
            imgs.block_until_ready()

    # ----------------------------------------------------------------- helpers

    def _pad(self, rows: np.ndarray) -> np.ndarray:
        """Pad a partial batch to the fixed jit width by repeating the
        tail row — one shape signature per stage, every occupancy."""
        short = self.cfg.batch - rows.shape[0]
        if short <= 0:
            return rows
        return np.concatenate([rows, np.repeat(rows[-1:], short, axis=0)], axis=0)

    def _clip_text(self, request) -> np.ndarray:
        """The rerank text is the request's own prompt row, truncated or
        zero-padded to the CLIP text length — one shared rerank path for
        the engine and the CLI (generate.py submits the tokenizer row as
        the prompt, so both see the same ids)."""
        L = self.spec.clip.text_seq_len
        row = np.zeros((L,), np.int32)
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        n = min(L, prompt.shape[0])
        row[:n] = prompt[:n]
        return row

    def _retry_or_degrade(self, batch: List[_Staged], now: float,
                          site: str) -> None:
        for st in batch:
            st.attempts += 1
            if st.attempts >= self.cfg.retry.attempts:
                self._staged.remove(st)
                self._degrade(st, site)
            else:
                self.counters.inc("serve.stage.retries")
                st.ready_at = now + self.cfg.retry.delay(st.attempts - 1)

    def _degrade(self, st: _Staged, detail: str) -> None:
        self.counters.inc("serve.stage.degraded")
        if st.image is None:
            self._finish(st.entry, Outcome.COMPLETED_TOKENS_ONLY, st.tokens,
                         detail=detail)
        else:
            self._finish(st.entry, Outcome.COMPLETED_UNRANKED, st.tokens,
                         image=st.image, detail=detail)

    def _complete(self, st: _Staged, score: Optional[float], now: float) -> None:
        self.histograms.observe("serve.stage.request_to_image_s",
                                max(0.0, now - st.entry.submit_time))
        self._finish(st.entry, Outcome.COMPLETED, st.tokens,
                     image=st.image, score=score)
