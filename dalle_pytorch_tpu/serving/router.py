"""Replicated serving front door: N engines behind one submit()/run().

The engine (engine.py) is a single point of failure — one stalled prefill
or crashed replica takes the whole serving path down. This module is the
host-side coordinator level of the FastUSP multi-level-collaboration
shape (PAPERS.md): a ``Router`` owns N in-process ``Engine`` replicas
(same model/params, per-replica metric labels, ONE shared clock) and
presents the engine's own ``submit()``/``run()`` API, with robustness —
failure detection, retry/backoff, graceful degradation — as the headline.
Sharding each replica with pjit partition rules is the follow-on
(ROADMAP item 2); here every replica is a full engine and the router is
pure host-side policy, unit-testable on CPU like the scheduler.

**Health state machine.** Each replica is HEALTHY → DEGRADED → DRAINING
→ DEAD, driven by two signals the engines already emit:

* *step-progress heartbeats*: per-replica labeled counters
  (``serve.decode_steps{replica=i}`` + ``serve.prefill_chunks`` +
  ``serve.admitted`` + harvested results). A replica with live work whose
  progress value does not move for ``stall_timeout_s`` on the shared
  clock is declared DEAD — the host-side analog of a hung device
  dispatch (injectable: ``replica_stall``).
* *the typed-outcome accounting invariant*: the router probes
  ``Engine.verify_invariants()`` every scheduling iteration; an engine
  that lost or duplicated a request is corrupt and is declared DEAD
  immediately — exactly the corruption the fleet exists to contain.

**Circuit breaker.** ``breaker_threshold`` consecutive prefill failures
(observed via the ``serve.prefill_retries{replica=i}`` counter delta,
reset by any successful admission) open the breaker: the replica is
DEGRADED — no new admissions, in-flight work continues — and readmitted
(→ HEALTHY) after a ``RetryPolicy`` exponential-backoff delay
(``breaker_backoff``; attempt i waits ``min(max_delay, base * 2**i)``,
full-jittered by the policy's ``jitter`` field through ONE router-owned
``random.Random(RouterConfig.backoff_seed)`` — deterministic under a
fixed seed, so chaos drills still replay exactly while a correlated
outage no longer re-collides every ladder in lockstep; the default
policies keep ``jitter=0.0``, which reproduces the historical
jitter-free schedule bit-for-bit). Re-trips back off further;
``breaker_backoff.attempts`` consecutive trips without an intervening
success escalate to DEAD.
The backoff is the admission-livelock guard: a flapping health signal
(injectable: ``health_flap``) makes the replica *progressively quieter*
instead of bouncing admissions forever.

**Routing.** Least-loaded: a queued request is dispatched to the HEALTHY
replica with the most free pages whose ``Engine.can_admit`` gate passes
(free slot, empty internal queue, worst-case demand fits free pages).
Dispatch-behind-the-gate keeps every replica's internal queue empty, so
the router never has to claw queued work back out of an engine — a
drain or crash only ever deals with in-flight slots. Head-of-line in
priority order, like the engine's own scheduler and for the same
anti-starvation reason.

**Failover.** When a replica dies (crash, stall timeout, invariant
violation, breaker escalation — injectable: ``replica_crash``), its
engine is abandoned the way a dead host's would be: unharvested results
are lost, and every in-flight request is requeued to the router and
re-dispatched to a sibling. Because sampling is keyed by per-request
``(seed, position)`` fold-ins and decode math is row-independent at
fixed batch width, the replay on the new replica is **bit-identical**
to an uninterrupted run — PR 3's preempt-and-requeue guarantee extended
across replica boundaries. Partial tokens from the dead replica are
discarded (replay regenerates them); ``max_failovers`` is the backstop
that turns a replica-death loop into the typed ``preempt_cap`` outcome.
A request's ``deadline`` stays an absolute instant on the ONE shared
clock injected into every replica, so a deadline that expires during
failover means the same moment on the new replica as on the old.

**Resurrection & durability** (docs/DESIGN.md §8.3). With
``RouterConfig.respawn`` on, a DEAD replica (any reason except an
operator drain) is rebuilt as a fresh ``Engine`` from the same
params/config after an exponential backoff — DEAD → RESPAWNING →
HEALTHY, the breaker's readmission discipline applied to process death
(``replica_respawn_fail`` injectable; ``max_respawns`` consecutive
failures retire it for good). A RESPAWNING replica's stale engine is
as abandoned as a dead one's, but its pending return HOLDS the
no-replica flush: queued work waits for the fleet to come back. With a
``RequestJournal`` attached, every admission and terminal outcome is
WAL-logged so a full-process crash replays unfinished requests
bit-identically on restart (serving/journal.py), and ``shutdown()`` is
the SIGTERM path: fleet-wide drain, journal seal, prefix snapshot.

**Global admission & load shedding.** The router's own bounded queue
rejects typed ``queue_full`` (with a ``router.shed`` event); demand that
can never fit a replica rejects ``demand_exceeds_pool``; a fleet with
no live replica rejects (and flushes its queue as) ``no_replica``.
Load-typed rejections (``queue_full``/``no_replica``) carry a
``retry_after_s`` hint — occupancy-scaled for sheds, the earliest
pending respawn for a dead fleet — observed into the
``router.retry_after_s`` histogram; well-behaved clients (the traffic
sim's closed-loop model) honor it instead of hammering a saturated
fleet on their own schedule.
Watermark degradation spans the fleet: every engine's clamp policy is
fed the *aggregate* occupancy over live replicas (``fleet_occupancy``
hook), so pressure anywhere — including capacity lost to a dead
sibling — degrades admissions everywhere, visibly
(``clamped_max_new_tokens`` in the response, as ever).

Observability: per-replica ``serve.*{replica=i}`` series (labeled
registries, utils/metrics.py), router counters/gauges under
``router.*``, a ``router.request`` lifecycle span per request ended with
its typed outcome, events ``router.failover`` / ``router.drain`` /
``router.shed`` / ``router.breaker_open`` / ``router.readmit``, and the
``router.failover_latency_s`` histogram (replica death → failover
dispatch). A dead replica's unclosed ``serve.request`` spans in a flight
recording are not corruption — they are the postmortem of what died
in flight, same contract as §9's crash captures.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.faults import FAULTS
from ..utils.metrics import counters, gauges, histograms
from ..utils.resilience import RetryPolicy, retry_after_hint
from ..utils.telemetry import TELEMETRY
from .engine import Engine, EngineConfig
from .journal import RequestJournal
from .types import Clock, Outcome, RejectReason, Request, RequestResult


class ReplicaState(str, Enum):
    """Health of one replica. str-valued for JSON-able stats, like
    ``Outcome``."""

    HEALTHY = "healthy"      # admitting and serving
    DEGRADED = "degraded"    # breaker open: no new admissions, serving
    DRAINING = "draining"    # operator drain: no new admissions, finishing
    DEAD = "dead"            # crashed / stalled / corrupt / retired
    # respawn policy (RouterConfig.respawn): awaiting its backoff-
    # scheduled rebuild — a fresh Engine from the same params/config.
    # The stale engine is already abandoned (in-flight work failed over
    # at death); the replica is not serving and not steppable.
    RESPAWNING = "respawning"


_STATE_CODE = {
    ReplicaState.HEALTHY: 0,
    ReplicaState.DEGRADED: 1,
    ReplicaState.DRAINING: 2,
    ReplicaState.DEAD: 3,
    ReplicaState.RESPAWNING: 4,
}

# states with a live, steppable engine (a RESPAWNING replica's engine is
# as abandoned as a DEAD one's — excluded from stepping, harvesting,
# occupancy aggregation, and engine-level invariant checks)
_ENGINE_DOWN = (ReplicaState.DEAD, ReplicaState.RESPAWNING)


@dataclass(frozen=True)
class RouterConfig:
    """Fleet-level knobs; per-replica behavior stays in ``EngineConfig``."""

    n_replicas: int = 2
    # router-level bounded admission queue (global, spans the fleet)
    queue_limit: int = 256
    # circuit breaker: consecutive prefill failures before DEGRADED
    breaker_threshold: int = 3
    # readmission schedule; .attempts consecutive trips escalate to DEAD.
    # retry_on is unused (nothing is raised); jitter draws from the
    # router's seeded backoff RNG (backoff_seed below) — the default 0.0
    # reproduces the historical deterministic schedule exactly.
    breaker_backoff: RetryPolicy = RetryPolicy(
        attempts=5, base_delay=1.0, max_delay=60.0, jitter=0.0,
        retry_on=(),
    )
    # heartbeat: busy with no step progress for this long (shared clock)
    # => the replica is declared DEAD and its work failed over
    stall_timeout_s: float = 30.0
    # replica deaths one request survives before the typed preempt_cap
    max_failovers: int = 3
    # replica resurrection: a DEAD replica (any reason except an operator
    # drain) is rebuilt as a fresh Engine from the same params/config
    # after a respawn_backoff delay (DEAD -> RESPAWNING -> HEALTHY; the
    # readmission discipline of the circuit breaker, applied to process
    # death). Failed attempts (``replica_respawn_fail``) back off
    # further; max_respawns consecutive failures retire the replica for
    # good. A successful respawn resets the ladder.
    respawn: bool = False
    max_respawns: int = 3
    respawn_backoff: RetryPolicy = RetryPolicy(
        attempts=3, base_delay=1.0, max_delay=60.0, jitter=0.0,
        retry_on=(),
    )
    # seeds the ONE router-owned RNG that draws backoff jitter for the
    # breaker and respawn ladders (full jitter, the ``RetryPolicy.delay``
    # formula). Fixed seed => bit-reproducible schedules, so chaos drills
    # and the traffic sim replay exactly; with both policies' jitter at
    # the 0.0 default the RNG is never consulted and the schedule is the
    # historical deterministic one.
    backoff_seed: int = 0


@dataclass
class _RouterEntry:
    """A request's fleet-level scheduling state (the router analog of
    ``scheduler.Entry``). Lives from router submit to router-terminal
    result; rides the router queue and then exactly one replica."""

    request: Request
    seq: int
    submit_time: float
    failovers: int = 0
    # set when a replica death requeued this entry; cleared (and observed
    # into router.failover_latency_s) at the failover dispatch
    crash_t0: Optional[float] = None
    # completed post-decode stage payloads (stage -> {"tokens": ids} /
    # {"image": ndarray}), mirrored from the pipeline's on_stage hook: a
    # FAILOVER of a staged request re-dispatches it from its last
    # completed stage (engine.submit_staged) instead of re-decoding —
    # the in-memory twin of the journal's stage records
    staged: Dict[str, dict] = field(default_factory=dict)

    @property
    def request_id(self) -> str:
        return self.request.request_id


class _Replica:
    """One engine plus its health bookkeeping."""

    def __init__(self, rid: int, engine: Engine, now: float):
        self.id = rid
        self.engine = engine
        self.state = ReplicaState.HEALTHY
        self.inflight: Dict[str, _RouterEntry] = {}
        self.death_reason: Optional[str] = None
        self.skip_steps = 0          # injected stall: steps to skip
        # respawn bookkeeping (RouterConfig.respawn)
        self.respawns = 0            # consecutive scheduled respawns
        self.respawn_at: Optional[float] = None
        self.death_t: Optional[float] = None
        self._reset_health(now)

    def _reset_health(self, now: float) -> None:
        """(Re)baseline every health signal — at construction AND at
        respawn. The baselines snapshot the CURRENT process-global
        labeled counters: a second Router in the same process (smoke/
        bench run clean + chaos passes back to back), or a respawned
        engine reusing this replica's label, must not inherit earlier
        retries as a spurious first-check delta that pops the breaker
        before any failure happened."""
        # heartbeat
        self.last_progress_t = now
        self.last_progress_val = self.progress_value()
        self.seen_retries = counters.get(
            "serve.prefill_retries", labels=self.labels
        )
        self.seen_admits = counters.get("serve.admitted", labels=self.labels)
        # circuit breaker
        self.breaker_consec = 0      # consecutive prefill failures
        self.breaker_trips = 0       # consecutive openings w/o a success
        self.retry_at: Optional[float] = None

    def rebind(self, engine: Engine, now: float) -> None:
        """Complete a respawn: adopt the fresh engine, rejoin the fleet
        HEALTHY, and close the respawn ladder (a successful resurrection
        resets it, like a successful admission closes the breaker's)."""
        self.engine = engine
        self.state = ReplicaState.HEALTHY
        self.death_reason = None
        self.respawns = 0
        self.respawn_at = None
        self.skip_steps = 0
        self._reset_health(now)

    @property
    def labels(self) -> dict:
        return {"replica": str(self.id)}

    def progress_value(self) -> int:
        """Monotone per-replica work tally — the heartbeat signal. Reads
        the same labeled counters an operator dashboard does."""
        c = counters
        return (
            c.get("serve.decode_steps", labels=self.labels)
            + c.get("serve.prefill_chunks", labels=self.labels)
            + c.get("serve.admitted", labels=self.labels)
            + len(self.engine.results)
        )


class Router:
    """See module docstring. Host-side fleet policy + N engines.

    Thread-safety: the router is the front door, so ``submit``/``cancel``
    may be called from serving threads while another thread drives
    ``run()``. All fleet-level bookkeeping (queue, results, live set,
    spans, tallies) is guarded by one RLock — ``_GUARDED_BY`` below is
    the machine-checked contract (tools/lint.py DTL051, docs/DESIGN.md
    §11); internal helpers use the ``*_locked`` caller-holds-the-lock
    convention. Each ``Engine`` stays single-threaded by design: only
    ``step()`` (under the lock) ever touches a replica's engine, so the
    engines need no locks of their own. Reentrancy (RLock) matters
    because an engine's ``fleet_occupancy`` hook calls back into the
    router mid-``step``."""

    _GUARDED_BY = {
        "_lock": ("_queue", "results", "_live", "_spans",
                  "_outcome_counts", "_seq", "_submitted",
                  "_draining_fleet"),
    }

    def __init__(self, dalle, params, config: RouterConfig = RouterConfig(),
                 engine_config: EngineConfig = EngineConfig(),
                 clock: Optional[Clock] = None,
                 journal: Optional[RequestJournal] = None,
                 engine_factory: Optional[Callable[..., Engine]] = None,
                 stages=None):
        assert config.n_replicas >= 1, config.n_replicas
        self.config = config
        self._lock = threading.RLock()
        self.clock = clock or Clock()
        # the respawn policy rebuilds a dead replica's engine from
        # exactly these — the same params/config every original got
        self._dalle = dalle
        self._params = params
        self._engine_config = engine_config
        # post-decode stages (serving/postdecode.py): a StageSpec enables
        # VAE decode + CLIP rerank on every replica engine; the router
        # binds each pipeline's stage-boundary hook to the journal and to
        # its failover bookkeeping (_RouterEntry.staged)
        self._stages = stages
        # replica construction seam: tools/traffic_sim.py substitutes a
        # modeled StubEngine fleet under the REAL router policy (health
        # machine, breaker, respawn, failover, shed). Called with
        # (rid, clock=, metric_labels=, fleet_occupancy=) at construction
        # AND at every respawn; None = build the real Engine.
        self._engine_factory = engine_factory
        # one RNG for every backoff draw (breaker + respawn ladders);
        # seeded so the jittered schedule replays bit-identically
        self._backoff_rng = random.Random(config.backoff_seed)
        # durable request journal (serving/journal.py): admissions and
        # terminal outcomes are logged so a full-process crash replays
        # unfinished requests bit-identically on restart. None = no
        # durability (the historical behavior).
        self._journal = journal
        now = self.clock.now()
        self._replicas: List[_Replica] = [
            _Replica(i, self._build_engine(i), now)
            for i in range(config.n_replicas)
        ]
        self._queue: List[_RouterEntry] = []
        self.results: Dict[str, RequestResult] = {}
        self._outcome_counts: Dict[Outcome, int] = {o: 0 for o in Outcome}
        self._spans: Dict[str, Optional[int]] = {}
        self._live: set = set()
        self._seq = 0
        self._submitted = 0
        self._draining_fleet = False

    def _build_engine(self, rid: int) -> Engine:
        """One replica's engine — used at construction and by every
        respawn, so a resurrected replica is the same build as the
        original (same model, params, config, shared clock, labels)."""
        if self._engine_factory is not None:
            return self._engine_factory(
                rid, clock=self.clock,
                metric_labels={"replica": str(rid)},
                fleet_occupancy=self.fleet_occupancy,
            )
        eng = Engine(
            self._dalle, self._params, self._engine_config,
            clock=self.clock, metric_labels={"replica": str(rid)},
            fleet_occupancy=self.fleet_occupancy,
            stages=self._stages,
        )
        if eng.postdecode is not None:
            # stage boundaries flow to the journal + failover state;
            # pipelines step inside engine.step(), which only runs under
            # the router lock — the RLock makes the re-entry safe
            eng.postdecode.on_stage = self._on_stage
        return eng

    # ------------------------------------------------------------ public

    def submit(self, request: Request) -> Optional[RequestResult]:
        """Queue a request with the fleet; same contract as
        ``Engine.submit`` — an immediate typed reject returns the result,
        otherwise None and the result lands in ``self.results``.
        Thread-safe: callable from serving threads while another thread
        drives ``run()``."""
        proto = self._replicas[0].engine
        if not (0 < request.max_new_tokens <= proto.dalle.image_seq_len):
            raise ValueError(
                f"max_new_tokens must be in [1, {proto.dalle.image_seq_len}], "
                f"got {request.max_new_tokens}"
            )
        with self._lock:
            if request.request_id in self.results or request.request_id in self._live:
                raise ValueError(f"duplicate request_id {request.request_id!r}")
            self._submitted += 1
            counters.inc("router.submitted")
            now = self.clock.now()
            self._spans[request.request_id] = TELEMETRY.begin(
                "router.request", request_id=request.request_id,
                priority=request.priority,
            )
            entry = _RouterEntry(request=request, seq=self._seq, submit_time=now)
            self._seq += 1
            live = [
                r for r in self._replicas if r.state is not ReplicaState.DEAD
            ]
            if not live:
                return self._reject_locked(entry, RejectReason.NO_REPLICA)
            # worst-case demand vs the LARGEST live pool: a request no
            # replica could ever hold is dead on arrival, fleet-wide
            worst = proto._worst_case_pages(request.max_new_tokens)
            if worst > max(r.engine.pool.total for r in live):
                return self._reject_locked(
                    entry, RejectReason.DEMAND_EXCEEDS_POOL
                )
            if len(self._queue) >= self.config.queue_limit:
                TELEMETRY.event(
                    "router.shed", request_id=request.request_id,
                    queued=len(self._queue),
                )
                counters.inc("router.shed")
                return self._reject_locked(entry, RejectReason.QUEUE_FULL)
            if self._journal is not None:
                # journal AFTER every typed-reject gate: the WAL holds
                # exactly the requests the fleet owes a terminal outcome
                self._journal.append_admitted(request, now)
            self._queue.append(entry)
            self._live.add(request.request_id)
            return None

    def submit_staged(self, request: Request, tokens,
                      image=None) -> Optional[RequestResult]:
        """Queue a request whose token work is already done — the crash
        replay resume path (``replay_unfinished(submit_staged=...)``): it
        dispatches straight into a replica's post-decode pipeline at the
        stage after its last journaled boundary. Same typed contract as
        ``submit``."""
        if self._stages is None:
            raise ValueError("router built without stages=StageSpec(...)")
        with self._lock:
            if request.request_id in self.results or request.request_id in self._live:
                raise ValueError(f"duplicate request_id {request.request_id!r}")
            self._submitted += 1
            counters.inc("router.submitted")
            now = self.clock.now()
            self._spans[request.request_id] = TELEMETRY.begin(
                "router.request", request_id=request.request_id,
                priority=request.priority,
            )
            entry = _RouterEntry(request=request, seq=self._seq,
                                 submit_time=now)
            self._seq += 1
            entry.staged["tokens"] = {
                "tokens": [int(t) for t in np.asarray(tokens).reshape(-1)]
            }
            if image is not None:
                entry.staged["vae_decode"] = {"image": image}
            live = [
                r for r in self._replicas if r.state is not ReplicaState.DEAD
            ]
            if not live:
                return self._reject_locked(entry, RejectReason.NO_REPLICA)
            # no page demand gate: staged work holds no kv pages
            if len(self._queue) >= self.config.queue_limit:
                TELEMETRY.event(
                    "router.shed", request_id=request.request_id,
                    queued=len(self._queue),
                )
                counters.inc("router.shed")
                return self._reject_locked(entry, RejectReason.QUEUE_FULL)
            if self._journal is not None:
                self._journal.append_admitted(request, now)
                # re-append the stage boundaries so THIS journal is
                # self-contained (idempotent: the loader keeps the last
                # record per stage)
                for stage, payload in entry.staged.items():
                    self._journal.append_stage(
                        request.request_id, stage, payload, now
                    )
            self._queue.append(entry)
            self._live.add(request.request_id)
            return None

    def cancel(self, request_id: str) -> None:
        """Cancel wherever the request currently lives: still queued at
        the router => terminal here next sweep; in flight on a replica =>
        forwarded to that engine (takes effect between its iterations)."""
        with self._lock:
            for entry in self._queue:
                if entry.request_id == request_id:
                    self._queue.remove(entry)
                    self._finish_locked(entry, RequestResult(
                        request_id=request_id, outcome=Outcome.CANCELLED,
                        total_latency_s=self.clock.now() - entry.submit_time,
                    ))
                    return
            for r in self._replicas:
                if r.state is not ReplicaState.DEAD and request_id in r.inflight:
                    r.engine.cancel(request_id)
                    return

    def drain(self, replica_id: int) -> None:
        """Graceful drain: stop admitting to the replica, let in-flight
        work finish, then retire it. Requests still queued at the router
        simply route to siblings (the ``can_admit`` dispatch gate means a
        replica's internal queue is already empty). Draining a
        RESPAWNING replica retires it immediately — its stale engine is
        already abandoned (nothing to finish) and a drain is operator
        retirement, so the pending respawn is cancelled rather than the
        dead engine re-activated."""
        with self._lock:
            r = self._replicas[replica_id]
            if r.state in (ReplicaState.DEAD, ReplicaState.DRAINING):
                return
            if r.state is ReplicaState.RESPAWNING:
                r.state = ReplicaState.DEAD
                r.respawn_at = None
                r.death_reason = "drained"
                counters.inc("router.drains")
                counters.inc("router.drained")
                TELEMETRY.event("router.drain", replica=r.id, inflight=0)
                TELEMETRY.event("router.drained", replica=r.id)
                return
            r.state = ReplicaState.DRAINING
            counters.inc("router.drains")
            TELEMETRY.event(
                "router.drain", replica=r.id, inflight=len(r.inflight),
            )

    def kill(self, replica_id: int, reason: str = "operator") -> None:
        """Declare a replica DEAD *now* and fail its in-flight work over
        to siblings — the abrupt form of ``drain`` (operator action or a
        test simulating a crash the fault registry didn't inject)."""
        with self._lock:
            r = self._replicas[replica_id]
            if r.state is not ReplicaState.DEAD:
                self._kill_locked(r, reason)

    def shutdown(self, snapshot_dir: Optional[str] = None,
                 max_steps: int = 10_000) -> None:
        """SIGTERM graceful drain (the serving analog of the trainer's
        emergency checkpoint; wired to ``PreemptionHandler.on_signal``
        by bench.py --serve and the smoke tools): stop admissions
        fleet-wide, drive until in-flight work finishes, then flush
        durable state — the journal is SEALED (sidecar manifest) and
        the prefix cache snapshotted to ``snapshot_dir`` (from the
        first live prefix-enabled engine). Requests still queued are
        deliberately NOT flushed typed: they stay journaled-unfinished,
        which is exactly what makes the next incarnation replay them
        bit-identically."""
        with self._lock:
            self._draining_fleet = True
            for r in self._replicas:
                if r.state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
                    r.state = ReplicaState.DRAINING
                    counters.inc("router.drains")
                    TELEMETRY.event(
                        "router.drain", replica=r.id,
                        inflight=len(r.inflight),
                    )
        steps = 0
        while True:
            with self._lock:
                busy = any(r.inflight for r in self._replicas)
            if not busy:
                break
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"shutdown drain made no progress in {max_steps} steps"
                )
        with self._lock:
            if snapshot_dir is not None:
                # snapshot the RICHEST non-empty index. A replica the
                # drain above just retired is eligible — "drained" means
                # its engine finished cleanly and its index is intact —
                # but crashed/corrupt engines are not, and an empty
                # index never overwrites an existing warm snapshot.
                candidates = [
                    r for r in self._replicas
                    if (
                        r.state not in _ENGINE_DOWN
                        or r.death_reason == "drained"
                    )
                    and r.engine.prefix is not None
                    and len(r.engine.prefix)
                ]
                if candidates:
                    best = max(
                        candidates, key=lambda r: len(r.engine.prefix)
                    )
                    best.engine.save_prefix_snapshot(snapshot_dir)
            if self._journal is not None:
                self._journal.seal()

    def live_requests(self) -> List[Request]:
        """Restorable descriptors of everything the fleet still owes a
        terminal outcome: router-queued requests (submission order) then
        per-replica in-flight ones — the crash-recovery export surface
        (journaled admissions already cover these; this is the
        journal-free export path and the invariant tests' oracle)."""
        with self._lock:
            queued = [
                e.request
                for e in sorted(self._queue, key=lambda e: e.seq)
            ]
            inflight = [
                entry.request
                for r in self._replicas
                for entry in sorted(
                    r.inflight.values(), key=lambda e: e.seq
                )
            ]
            return queued + inflight

    def step(self) -> bool:
        """One fleet scheduling iteration: fault injections -> router
        deadline sweep -> drive + harvest every live replica -> health
        checks -> retire finished drains -> dispatch -> all-dead flush.
        Returns False when the fleet is fully idle. The whole iteration
        runs under the router lock: concurrent ``submit``/``cancel``
        land between iterations, never inside one."""
        with self._lock:
            self._inject_faults_locked()
            self._sweep_queue_deadlines_locked()
            stepped = 0
            for r in self._replicas:
                if r.state in _ENGINE_DOWN:
                    continue
                if r.skip_steps > 0:
                    r.skip_steps -= 1   # injected stall: the engine hangs
                else:
                    r.engine.step()
                    stepped += 1
                self._harvest_locked(r)
            for r in self._replicas:
                if r.state not in _ENGINE_DOWN:
                    self._health_check_locked(r)
            self._respawn_sweep_locked()
            for r in self._replicas:
                if (
                    r.state is ReplicaState.DRAINING
                    and not r.inflight
                    and not any(r.engine.slots)
                    and not len(r.engine.sched)
                    and not getattr(r.engine, "postdecode", None)
                ):
                    r.state = ReplicaState.DEAD
                    r.death_reason = "drained"
                    counters.inc("router.drained")
                    TELEMETRY.event("router.drained", replica=r.id)
            self._dispatch_locked()
            # RESPAWNING replicas hold the flush: the fleet will come
            # back, so queued work WAITS instead of flushing typed (a
            # shutdown drain also holds it — queued work stays journaled
            # for the next incarnation to replay)
            if (
                all(r.state is ReplicaState.DEAD for r in self._replicas)
                and not self._draining_fleet
            ):
                self._flush_no_replica_locked()
            if stepped == 0:
                # every replica dead/stalled: time must still advance
                # (engine steps normally tick the shared clock) or
                # deadline sweeps and the stall heartbeat itself would
                # freeze with it
                self.clock.tick()
            self._publish_gauges_locked()
            return bool(self._queue) or any(
                r.inflight for r in self._replicas
            )

    def run(self, max_steps: Optional[int] = None) -> Dict[str, RequestResult]:
        """Drive until idle; ``max_steps`` is the same loud safety valve
        as ``Engine.run``."""
        steps = 0
        while self.step():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                with self._lock:
                    raise RuntimeError(
                        f"router made no terminal progress in {max_steps} "
                        f"steps: {len(self._queue)} queued, "
                        f"{sum(len(r.inflight) for r in self._replicas)} "
                        f"in flight"
                    )
        with self._lock:
            return self.results

    def fleet_occupancy(self) -> float:
        """Aggregate page occupancy over LIVE replicas — capacity lost to
        a dead sibling raises the remaining fleet's pressure, which is
        what lets the watermark clamp degrade admissions fleet-wide.
        Locked: a monitoring thread must never read replica states and
        pool tallies mid-``step`` (reentrant for the engine's own
        mid-step callback — the RLock)."""
        with self._lock:
            live = [
                r for r in self._replicas if r.state not in _ENGINE_DOWN
            ]
            total = sum(r.engine.pool.total for r in live)
            if total == 0:
                return 1.0
            return sum(r.engine.pool.used for r in live) / total

    def replica_states(self) -> Dict[int, str]:
        with self._lock:
            return {r.id: r.state.value for r in self._replicas}

    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self._submitted,
                "queued": len(self._queue),
                "fleet_occupancy": self.fleet_occupancy(),
                "outcomes": {
                    o.value: n for o, n in self._outcome_counts.items()
                },
                "replicas": {
                    r.id: {
                        "state": r.state.value,
                        "death_reason": r.death_reason,
                        "inflight": len(r.inflight),
                        "pool_occupancy": r.engine.pool.occupancy,
                        "breaker_trips": r.breaker_trips,
                        "respawns": r.respawns,
                    }
                    for r in self._replicas
                },
            }

    def verify_invariants(self) -> None:
        """Fleet-level accounting: every submitted request is live XOR has
        exactly one router result (none lost, none duplicated), the live
        set is exactly queue + in-flight, every live engine's own
        invariants hold, and every live engine's live requests are tracked
        by the router."""
        with self._lock:
            inflight_ids = set()
            for r in self._replicas:
                assert not (inflight_ids & set(r.inflight)), \
                    "request on two replicas"
                inflight_ids |= set(r.inflight)
            queued_ids = {e.request_id for e in self._queue}
            both = [rid for rid in self._live if rid in self.results]
            assert not both, f"request both live and finished: {sorted(both)}"
            assert len(self.results) + len(self._live) == self._submitted, (
                f"{self._submitted} submitted but {len(self.results)} results "
                f"+ {len(self._live)} live"
            )
            assert self._live == queued_ids | inflight_ids, (
                f"live {sorted(self._live)} != queued {sorted(queued_ids)} | "
                f"inflight {sorted(inflight_ids)}"
            )
            outcomes = self.stats()["outcomes"]
            assert sum(outcomes.values()) == len(self.results), outcomes
            for r in self._replicas:
                if r.state not in _ENGINE_DOWN:
                    r.engine.verify_invariants()
                    assert r.engine._live <= set(r.inflight), (
                        f"replica {r.id} serving untracked requests "
                        f"{sorted(r.engine._live - set(r.inflight))}"
                    )
                else:
                    assert not r.inflight, (
                        f"replica {r.id} is {r.state.value} but still "
                        f"tracks in-flight work {sorted(r.inflight)}"
                    )

    # ---------------------------------------------------------- injections

    def _inject_faults_locked(self) -> None:
        # eligibility is checked BEFORE take(): an armed fault with no
        # eligible victim stays armed for the next iteration instead of
        # being silently swallowed
        victim = self._busiest_live()
        if victim is not None and FAULTS.take("replica_crash"):
            counters.inc("router.fault_replica_crash")
            self._kill_locked(victim, "crash")
            victim = self._busiest_live()
        if victim is not None and FAULTS.take("replica_stall"):
            counters.inc("router.fault_replica_stall")
            victim.skip_steps += 1
        healthy = [
            r for r in self._replicas if r.state is ReplicaState.HEALTHY
        ]
        if healthy and FAULTS.take("health_flap"):
            counters.inc("router.fault_health_flap")
            self._open_breaker_locked(healthy[0], "health_flap")

    def _busiest_live(self) -> Optional[_Replica]:
        live = [r for r in self._replicas if r.state not in _ENGINE_DOWN]
        if not live:
            return None
        return max(live, key=lambda r: (len(r.inflight), -r.id))

    # ------------------------------------------------------------- health

    def _health_check_locked(self, r: _Replica) -> None:
        # accounting invariant: a corrupt engine is dead NOW — routing
        # more work into it can only lose or duplicate requests
        try:
            r.engine.verify_invariants()
        except AssertionError as e:
            TELEMETRY.event(
                "router.invariant_violation", replica=r.id, detail=str(e)[:200]
            )
            self._kill_locked(r, "invariant_violation")
            return
        now = self.clock.now()
        # circuit breaker: consecutive prefill failures via counter deltas
        retries = counters.get("serve.prefill_retries", labels=r.labels)
        admits = counters.get("serve.admitted", labels=r.labels)
        d_retry = retries - r.seen_retries
        d_admit = admits - r.seen_admits
        r.seen_retries, r.seen_admits = retries, admits
        if d_admit > 0:
            r.breaker_consec = 0
            r.breaker_trips = 0  # a success closes the escalation ladder
        r.breaker_consec += d_retry
        if (
            r.state is ReplicaState.HEALTHY
            and r.breaker_consec >= self.config.breaker_threshold
        ):
            self._open_breaker_locked(r, "prefill_failures")
        # breaker readmission after backoff
        if (
            r.state is ReplicaState.DEGRADED
            and r.retry_at is not None
            and now >= r.retry_at
        ):
            r.state = ReplicaState.HEALTHY
            r.retry_at = None
            counters.inc("router.readmits")
            TELEMETRY.event(
                "router.readmit", replica=r.id, trips=r.breaker_trips
            )
        # step-progress heartbeat
        progress = r.progress_value()
        if progress != r.last_progress_val or not r.inflight:
            r.last_progress_val = progress
            r.last_progress_t = now
        elif now - r.last_progress_t > self.config.stall_timeout_s:
            self._kill_locked(r, "stall_timeout")

    def _open_breaker_locked(self, r: _Replica, reason: str) -> None:
        policy = self.config.breaker_backoff
        r.breaker_trips += 1
        r.breaker_consec = 0
        if r.breaker_trips > max(1, policy.attempts):
            self._kill_locked(r, "breaker_exhausted")
            return
        delay = policy.delay(r.breaker_trips - 1, self._backoff_rng)
        r.retry_at = self.clock.now() + delay
        r.state = ReplicaState.DEGRADED
        counters.inc("router.breaker_opens")
        TELEMETRY.event(
            "router.breaker_open", replica=r.id, reason=reason,
            trips=r.breaker_trips, retry_in_s=delay,
        )

    # ----------------------------------------------------------- failover

    def _kill_locked(self, r: _Replica, reason: str) -> None:
        """Declare a replica dead and fail its in-flight work over. The
        engine is abandoned like a dead host: unharvested results are
        lost; requeued requests replay from scratch on a sibling —
        bit-identically, by the (seed, position) sampling contract."""
        r.state = ReplicaState.DEAD
        r.death_reason = reason
        counters.inc("router.replica_deaths")
        now = self.clock.now()
        r.death_t = now
        if self.config.respawn:
            self._schedule_respawn_locked(r)
        TELEMETRY.event(
            "router.failover", replica=r.id, reason=reason,
            inflight=len(r.inflight),
        )
        for rid, entry in sorted(r.inflight.items(), key=lambda kv: kv[1].seq):
            entry.failovers += 1
            entry.crash_t0 = now
            if entry.failovers > self.config.max_failovers:
                self._finish_locked(entry, RequestResult(
                    request_id=rid, outcome=Outcome.PREEMPT_CAP,
                    preempt_count=entry.failovers,
                    total_latency_s=now - entry.submit_time,
                    detail=f"lost {entry.failovers} replicas "
                           f"(max_failovers {self.config.max_failovers})",
                ))
            else:
                self._queue.append(entry)
        r.inflight.clear()

    # ----------------------------------------------------------- respawn

    def _schedule_respawn_locked(self, r: _Replica) -> None:
        """DEAD -> RESPAWNING with an exponential-backoff rebuild time —
        or permanently DEAD once the ladder is exhausted. Jittered like
        the breaker (the shared seeded RNG): a correlated outage that
        kills N replicas at once must NOT schedule N rebuilds for the
        same instant, or the herd re-collides on respawn — with the
        default ``jitter=0.0`` the schedule is the historical
        deterministic one."""
        if r.respawns >= self.config.max_respawns:
            r.respawn_at = None
            r.death_reason = f"{r.death_reason} (respawns exhausted)"
            TELEMETRY.event(
                "router.respawn_fail", replica=r.id,
                attempts=r.respawns, exhausted=True,
            )
            return
        policy = self.config.respawn_backoff
        delay = policy.delay(r.respawns, self._backoff_rng)
        r.respawns += 1
        r.respawn_at = self.clock.now() + delay
        r.state = ReplicaState.RESPAWNING

    def _respawn_sweep_locked(self) -> None:
        """Attempt every due respawn: rebuild the engine from the SAME
        params/config and readmit the replica HEALTHY, re-baselining
        every health signal. The ``replica_respawn_fail`` fault fails
        the attempt — back to the backoff ladder (further out each
        time), permanently DEAD once exhausted."""
        if self._draining_fleet:
            return  # a draining fleet resurrects nobody
        now = self.clock.now()
        for r in self._replicas:
            if r.state is not ReplicaState.RESPAWNING:
                continue
            if r.respawn_at is None or now < r.respawn_at:
                continue
            if FAULTS.take("replica_respawn_fail"):
                counters.inc("router.fault_replica_respawn_fail")
                TELEMETRY.event(
                    "router.respawn_fail", replica=r.id,
                    attempts=r.respawns, exhausted=False,
                )
                r.state = ReplicaState.DEAD
                self._schedule_respawn_locked(r)
                continue
            r.rebind(self._build_engine(r.id), now)
            counters.inc("router.respawns")
            recovery = None if r.death_t is None else now - r.death_t
            if recovery is not None:
                # kill -> healthy MTTR, per replica (the bench.py --serve
                # recovery record reads this histogram)
                histograms.observe(
                    "serve.recovery_s", recovery, labels=r.labels
                )
            TELEMETRY.event(
                "router.respawn", replica=r.id, recovery_s=recovery,
            )

    def _flush_no_replica_locked(self) -> None:
        """Fleet fully dead: every queued request ends typed rather than
        hanging — the none-lost half of the accounting invariant."""
        hint = self._retry_after_locked(RejectReason.NO_REPLICA)
        for entry in list(self._queue):
            self._queue.remove(entry)
            counters.inc("router.no_replica")
            if hint is not None:
                histograms.observe("router.retry_after_s", hint)
            self._finish_locked(entry, RequestResult(
                request_id=entry.request_id, outcome=Outcome.REJECTED,
                reject_reason=RejectReason.NO_REPLICA,
                total_latency_s=self.clock.now() - entry.submit_time,
                retry_after_s=hint,
                detail="fleet has no live replica",
            ))

    def _retry_after_locked(
        self, reason: RejectReason,
    ) -> Optional[float]:
        """Backoff hint for a load-typed rejection (the
        ``RequestResult.retry_after_s`` satellite of the traffic sim).
        QUEUE_FULL scales the breaker ladder's base delay by fleet
        occupancy (``retry_after_hint``); NO_REPLICA answers with the
        fleet's ACTUAL comeback time — the earliest pending respawn —
        falling back to one respawn-ladder rung when nothing is
        scheduled. DEMAND_EXCEEDS_POOL gets None: the demand can never
        fit, retrying is futile and hinting otherwise would invite a
        permanent retry loop."""
        if reason is RejectReason.QUEUE_FULL:
            policy = self.config.breaker_backoff
            return retry_after_hint(
                self.fleet_occupancy(),
                base_delay=policy.base_delay, max_delay=policy.max_delay,
            )
        if reason is RejectReason.NO_REPLICA:
            now = self.clock.now()
            pending = [
                r.respawn_at - now
                for r in self._replicas
                if r.state is ReplicaState.RESPAWNING
                and r.respawn_at is not None
            ]
            if pending:
                return max(0.0, min(pending))
            return self.config.respawn_backoff.base_delay
        return None

    # ----------------------------------------------------------- dispatch

    def _sweep_queue_deadlines_locked(self) -> None:
        now = self.clock.now()
        for entry in list(self._queue):
            d = entry.request.deadline
            if d is not None and now > d:
                self._queue.remove(entry)
                self._finish_locked(entry, RequestResult(
                    request_id=entry.request_id,
                    outcome=Outcome.DEADLINE_EXCEEDED,
                    total_latency_s=now - entry.submit_time,
                    detail="deadline passed in router queue",
                ))

    def _dispatch_locked(self) -> None:
        """Route queued work: head-of-line in (priority, FIFO) order to
        the least-loaded admittable HEALTHY replica. Strict head-of-line
        (nothing behind a stuck head goes first) for the scheduler's
        anti-starvation reason."""
        # one sort per pass: nothing is appended to the queue while this
        # loop runs (submits and failover requeues happen between steps)
        self._queue.sort(key=lambda e: (-e.request.priority, e.seq))
        while self._queue:
            entry = self._queue[0]
            # a staged entry (completed stage payloads from the journal or
            # a dead replica) resumes INSIDE a pipeline, not a slot — its
            # admission gate and submit path differ
            staged = "tokens" in entry.staged
            candidates = [
                r for r in self._replicas
                if r.state is ReplicaState.HEALTHY
                and (
                    r.engine.can_admit_staged(entry.request) if staged
                    else r.engine.can_admit(entry.request)
                )
            ]
            if not candidates:
                return
            r = max(candidates, key=lambda c: (c.engine.pool.free, -c.id))
            self._queue.pop(0)
            now = self.clock.now()
            if entry.crash_t0 is not None:
                latency = now - entry.crash_t0
                histograms.observe("router.failover_latency_s", latency)
                counters.inc("router.failovers")
                TELEMETRY.event(
                    "router.failover_dispatch",
                    request_id=entry.request_id, replica=r.id,
                    latency_s=latency, failovers=entry.failovers,
                )
                entry.crash_t0 = None
            if staged:
                img = entry.staged.get("vae_decode")
                rejected = r.engine.submit_staged(
                    entry.request,
                    np.asarray(entry.staged["tokens"]["tokens"], np.int32),
                    image=None if img is None else img["image"],
                )
            else:
                rejected = r.engine.submit(entry.request)
            if rejected is not None:
                # can_admit said yes but the engine refused — surface the
                # engine's typed reason rather than hiding a router bug
                self._finish_locked(entry, rejected)
                continue
            r.inflight[entry.request_id] = entry

    # ------------------------------------------------------------ harvest

    def _harvest_locked(self, r: _Replica) -> None:
        for rid in list(r.inflight):
            res = r.engine.results.get(rid)
            if res is None:
                continue
            entry = r.inflight.pop(rid)
            if entry.failovers:
                res.detail = (
                    f"{res.detail} (failovers={entry.failovers})".strip()
                )
            self._finish_locked(entry, res)

    # ----------------------------------------------------------- plumbing

    def _reject_locked(self, entry: _RouterEntry, reason: RejectReason) -> RequestResult:
        hint = self._retry_after_locked(reason)
        if hint is not None:
            histograms.observe("router.retry_after_s", hint)
        result = RequestResult(
            request_id=entry.request_id,
            outcome=Outcome.REJECTED,
            reject_reason=reason,
            total_latency_s=0.0,
            retry_after_s=hint,
        )
        self._finish_locked(entry, result)
        return result

    def _on_stage(self, request_id: str, stage: str, payload: dict) -> None:
        """Stage-boundary sink for every replica pipeline: journal the
        record durably (crash replay) and mirror it onto the in-flight
        entry (replica failover). Called from inside ``engine.step()``,
        which already holds the router lock — the RLock re-entry is
        free."""
        with self._lock:
            if self._journal is not None:
                self._journal.append_stage(
                    request_id, stage, payload, self.clock.now()
                )
            for r in self._replicas:
                entry = r.inflight.get(request_id)
                if entry is not None:
                    entry.staged[stage] = payload
                    break

    def _finish_locked(self, entry: _RouterEntry, result: RequestResult) -> None:
        assert entry.request_id not in self.results, (
            f"duplicate terminal result for {entry.request_id!r}"
        )
        self._live.discard(entry.request_id)
        self.results[entry.request_id] = result
        if self._journal is not None:
            # the completion record that makes crash replay idempotent
            self._journal.append_outcome(
                entry.request_id, result.outcome.value, self.clock.now()
            )
        self._outcome_counts[result.outcome] += 1
        counters.inc(f"router.{result.outcome.value}")
        TELEMETRY.end(
            self._spans.pop(entry.request_id, None),
            outcome=result.outcome.value,
            reject_reason=(
                None if result.reject_reason is None
                else result.reject_reason.value
            ),
            failovers=entry.failovers,
        )

    def _publish_gauges_locked(self) -> None:
        gauges.set("router.queued", len(self._queue))
        gauges.set("router.fleet_occupancy", self.fleet_occupancy())
        gauges.set("router.replicas_live", sum(
            r.state not in _ENGINE_DOWN for r in self._replicas
        ))
        for r in self._replicas:
            gauges.set(
                "router.replica_state_code", _STATE_CODE[r.state],
                labels=r.labels,
            )
