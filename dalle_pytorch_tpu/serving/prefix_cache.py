"""Content-addressed index over immutable, fully written KV pages —
cross-request prefix caching (ROADMAP 3; FastUSP's shared-resource
framing, PAPERS.md 2602.10940).

Production text-to-image traffic is heavily templated: shared system /
style prompt prefixes and retried prompts re-run identical prefill work
on every request. The block-paged layout (PR 1) and the global-id page
tables (ops/paged_kv.py) make deduplicating that work a PAGE-TABLE
INDIRECTION: this module indexes already-computed prompt KV pages by the
HASH CHAIN of the token ids they cover, and the engine maps hit pages
into an admitted slot's table read-only instead of recomputing them.

The index is pure HOST bookkeeping — no jax import, no device arrays of
its own. Physical page content lives in dedicated ARENA rows of the
engine's batched cache pools (rows past the slot rows, reachable only
through remapped table entries); this module owns the arena ID space and
the chain index, while the engine performs every device copy
(``paged_kv.copy_pages``) and table write. The ring-seam and
terminal-logits payloads are stored as opaque objects (device arrays in
practice) — captured by the engine at prefill page boundaries, restored
by the engine at resume.

Chain addressing: the prompt's internal token row is cut into page-sized
blocks plus one terminal partial block ending at T; node ``k``'s digest
is ``sha1(parent_digest || block_bytes)``, so two prompts share exactly
the nodes of their common page-aligned prefix. Every lookup VERIFIES the
stored token block against the query before a page is mapped — the hash
is an address, never a proof — and the ``prefix_hash_collide`` fault
site forces a forged lookup result so tests can pin that a collision
falls back to cold prefill instead of serving another prompt's K/V.

Refcount invariants (asserted by ``Engine.verify_invariants``):

* ``node.refcount`` == number of live slots currently mapping the node's
  page; acquire/release are engine-driven and symmetric across every
  termination path (complete / preempt / deadline / cancel).
* a node with ``refcount > 0`` is NEVER an eviction victim — shared
  pages are not reclaimable while any sequence can still gather them;
* eviction is leaf-first (``children == 0``; an interior node's eviction
  would orphan reachable descendants) and LRU by ``last_hit`` — the
  index is its own eviction tier: unreferenced cache pages are dropped
  to free budget BEFORE any running request is preempted (a preemption
  discards real work; an index page only costs future recompute).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.faults import FAULTS

_ROOT = b"prefix-cache-root"


def chain_root(format_tag: bytes = b"") -> bytes:
    """The chain's root parent digest. A non-empty ``format_tag`` (the
    engine's KV storage-format descriptor: quantization, pool/scale
    dtypes, page size) SALTS the root, so every digest in the chain
    addresses (KV format, tokens) — page content is a deterministic
    function of exactly that pair, which is how the content hash COVERS
    the quantized bytes + scales without syncing device arrays into a
    hasher on the publish path. Two engines with different KV formats
    therefore can never exchange chain addresses (a quantized snapshot
    offered to an f32 engine misses at the root, before the leaf-dtype
    checks even run). The empty tag preserves the pre-quantization
    address space for the default unquantized format."""
    if not format_tag:
        return _ROOT
    return hashlib.sha1(_ROOT + format_tag).digest()


def chain_blocks(tokens: np.ndarray, page_size: int) -> List[np.ndarray]:
    """Cut a prompt's internal token row into its chain blocks: full
    ``page_size`` blocks plus one terminal partial block ending at T
    (absent only when T divides evenly — then the last full block IS the
    terminal). Block k covers positions [k * page_size, ...)."""
    t = np.asarray(tokens, np.int64).reshape(-1)
    return [t[i: i + page_size] for i in range(0, len(t), page_size)]


def _digest(parent: bytes, block: np.ndarray) -> bytes:
    return hashlib.sha1(
        parent + np.asarray(block, np.int64).tobytes()
    ).digest()


def chain_digest(parent: Optional[bytes], block: np.ndarray,
                 format_tag: bytes = b"") -> bytes:
    """Public chain-digest derivation (``parent=None`` = chain root,
    salted by ``format_tag`` — see ``chain_root``) — shared by the index
    itself and the snapshot verifier, so a persisted node's address can
    be recomputed from its tokens and checked against what was stored
    (verify-on-load is mandatory: the hash is an address, never a
    proof; docs/DESIGN.md §8.3)."""
    return _digest(chain_root(format_tag) if parent is None else parent, block)


def snapshot_records(cache: "PrefixCache") -> List[dict]:
    """The index's JSON-able structure for a snapshot, topologically
    ordered (parents strictly precede children — a parent's ``start`` is
    strictly smaller, so a ``start`` sort is a topological sort; ties
    are independent chains). Opaque device payloads (ring seams,
    terminal logits) are NOT here — the engine persists those next to
    the page bytes; these records carry the addressing and the tokens
    the verifier recomputes digests from."""
    nodes = sorted(cache.nodes(), key=lambda n: (n.start, n.digest))
    return [
        {
            "digest": n.digest.hex(),
            "parent": None if n.parent is None else n.parent.hex(),
            "tokens": [int(t) for t in np.asarray(n.tokens).reshape(-1)],
            "start": int(n.start),
            "page_id": int(n.page_id),
            "has_ring": n.ring is not None,
            "has_logits": n.logits is not None,
        }
        for n in nodes
    ]


def verify_snapshot_records(records: List[dict], page_size: int,
                            format_tag: bytes = b"") -> Tuple[bool, str]:
    """Mandatory verify-on-load for a persisted index: every record's
    digest must RECOMPUTE from its parent digest + stored tokens (a
    flipped token or forged digest fails here), parents must precede
    their children, block sizes must fit the page, and coverage must be
    contiguous from the parent. -> (ok, reason); any failure rejects
    the WHOLE snapshot — the engine falls back to a cold index rather
    than mapping unverified K/V."""
    seen: Dict[str, dict] = {}
    for i, rec in enumerate(records):
        try:
            tokens = np.asarray(rec["tokens"], np.int64)
            start = int(rec["start"])
            digest = bytes.fromhex(rec["digest"])
            parent_hex = rec["parent"]
        except (KeyError, TypeError, ValueError) as e:
            return False, f"record {i}: malformed ({e})"
        if rec["digest"] in seen:
            return False, (
                f"record {i}: duplicate chain node (dedup-on-insert "
                "would be violated at restore)"
            )
        if not (0 < len(tokens) <= page_size):
            return False, (
                f"record {i}: block of {len(tokens)} tokens does not fit "
                f"page size {page_size}"
            )
        if parent_hex is None:
            parent_bytes = None
            if start != 0:
                return False, f"record {i}: root block at start {start}"
        else:
            parent = seen.get(parent_hex)
            if parent is None:
                return False, (
                    f"record {i}: parent {parent_hex[:12]} missing or "
                    "out of order"
                )
            parent_bytes = bytes.fromhex(parent_hex)
            expect = int(parent["start"]) + len(parent["tokens"])
            if start != expect:
                return False, (
                    f"record {i}: start {start} not contiguous with "
                    f"parent coverage {expect}"
                )
        if chain_digest(parent_bytes, tokens, format_tag) != digest:
            return False, (
                f"record {i}: stored digest does not recompute from its "
                "tokens (corrupt block or forged address)"
            )
        seen[rec["digest"]] = rec
    return True, "ok"


@dataclass
class PageNode:
    """One immutable, fully written KV page, content-addressed by the
    hash chain of the token ids it covers. ``page_id`` is the GLOBAL
    physical page (an arena page of the engine's pools); ``valid`` the
    written row count (== page_size except the terminal block); ``ring``
    the opaque shift-ring seam at position ``coverage`` (present iff the
    publisher observed that boundary — the resume requirement); and
    ``logits`` the terminal image-head logits (full-prefix nodes only —
    what lets a full hit sample its first token without any prefill)."""

    digest: bytes
    parent: Optional[bytes]
    tokens: np.ndarray
    start: int
    page_id: int
    ring: Any = None
    logits: Any = None
    refcount: int = 0
    last_hit: float = 0.0
    children: int = 0

    @property
    def coverage(self) -> int:
        return self.start + len(self.tokens)

    @property
    def valid(self) -> int:
        return len(self.tokens)

    @property
    def resumable(self) -> bool:
        """A node the engine can RESUME prefill from (or, with logits,
        enter decode from): it carries the shift-ring seam at its
        coverage boundary."""
        return self.ring is not None


@dataclass
class PrefixStats:
    hits: int = 0
    misses: int = 0
    collisions: int = 0
    published: int = 0
    deduped: int = 0
    evicted: int = 0
    publish_skips: int = 0


class PrefixCache:
    """See module docstring. Single-threaded like the engine that owns
    it (the engine's scheduling loop is the only caller)."""

    def __init__(self, arena_page_ids: Sequence[int], page_size: int,
                 format_tag: bytes = b""):
        assert page_size > 0, page_size
        self.page_size = page_size
        self.format_tag = format_tag
        self._root = chain_root(format_tag)
        self.arena_total = len(arena_page_ids)
        self._free_pages: List[int] = list(arena_page_ids)
        self._nodes: Dict[bytes, PageNode] = {}
        self.stats = PrefixStats()

    # ------------------------------------------------------------- sizing
    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def free_arena_pages(self) -> int:
        return len(self._free_pages)

    def nodes(self) -> List[PageNode]:
        """Every indexed node (invariant checks / tests)."""
        return list(self._nodes.values())

    def total_refs(self) -> int:
        return sum(n.refcount for n in self._nodes.values())

    # -------------------------------------------------------------- probe
    def _lookup_child(self, parent: bytes, block: np.ndarray) -> Optional[PageNode]:
        """Address the child by chain digest, then VERIFY the stored
        token block — the hash is an address, never a proof. The
        ``prefix_hash_collide`` fault forges the lookup result (returns a
        node whose stored block does not match the query) so the
        verification path is drillable on CPU."""
        digest = _digest(parent, block)
        node = self._nodes.get(digest)
        # index-emptiness guard FIRST: take() consumes the armed count on
        # every call, and an env-armed drill must spend its budget on a
        # probe that can actually forge a node (the cold round's probes
        # run against an empty index)
        if self._nodes and FAULTS.take("prefix_hash_collide"):
            node = next(iter(self._nodes.values()))
            if np.array_equal(
                np.asarray(node.tokens, np.int64), np.asarray(block, np.int64)
            ):
                node = PageNode(
                    digest=digest, parent=parent,
                    tokens=np.asarray(block, np.int64) + 1,
                    start=node.start, page_id=node.page_id,
                )
        if node is None:
            return None
        if not np.array_equal(
            np.asarray(node.tokens, np.int64), np.asarray(block, np.int64)
        ):
            self.stats.collisions += 1
            return None
        return node

    def probe(
        self, tokens: np.ndarray, now: float, count: bool = True
    ) -> List[PageNode]:
        """Walk the prompt's chain and return the VERIFIED matched prefix
        nodes (possibly empty). Touches ``last_hit`` on every matched
        node; does NOT take references — the engine acquires exactly the
        nodes it maps. ``count=False`` skips the hit/miss tally: the
        engine re-probes a page-blocked head-of-line request every
        scheduling iteration and counts ONE hit or miss per admission
        (in ``_note_prefix_outcome``), so its stats stay in lockstep
        with the ``serve.prefix.*`` counters."""
        out: List[PageNode] = []
        parent = self._root
        for block in chain_blocks(tokens, self.page_size):
            node = self._lookup_child(parent, block)
            if node is None:
                break
            node.last_hit = now
            out.append(node)
            parent = node.digest
        if count:
            if out:
                self.stats.hits += 1
            else:
                self.stats.misses += 1
        return out

    def match(self, tokens: np.ndarray) -> List[PageNode]:
        """The probe walk WITHOUT hit/miss accounting, recency touches,
        or fault injection — the publish path's dedup check (a publisher
        consulting the chain is not a cache consumer)."""
        out: List[PageNode] = []
        parent = self._root
        for block in chain_blocks(tokens, self.page_size):
            node = self._nodes.get(_digest(parent, block))
            if node is None or not np.array_equal(
                np.asarray(node.tokens, np.int64), np.asarray(block, np.int64)
            ):
                break
            out.append(node)
            parent = node.digest
        return out

    # ---------------------------------------------------------- refcounts
    def acquire(self, nodes: Sequence[PageNode], now: float) -> None:
        for n in nodes:
            assert n.digest in self._nodes, "acquire of evicted node"
            n.refcount += 1
            n.last_hit = now

    def release(self, nodes: Sequence[PageNode]) -> None:
        for n in nodes:
            assert n.refcount > 0, (
                f"refcount underflow for node at {n.start}"
            )
            n.refcount -= 1

    # ------------------------------------------------------------ publish
    def alloc_page(self) -> Optional[int]:
        """Pop a free arena page id; None when the arena is exhausted
        (the engine then evicts LRU unreferenced nodes or fails open)."""
        return self._free_pages.pop() if self._free_pages else None

    def return_page(self, page_id: int) -> None:
        """Hand back a page allocated but never committed (a publish that
        failed between alloc and insert)."""
        self._free_pages.append(page_id)

    def insert(
        self,
        parent: Optional[PageNode],
        block: np.ndarray,
        start: int,
        page_id: int,
        now: float,
        ring: Any = None,
        logits: Any = None,
    ) -> PageNode:
        """Commit one published page (dedup is the CALLER's probe-first
        protocol: inserting an existing chain position is a bug)."""
        parent_digest = self._root if parent is None else parent.digest
        digest = _digest(parent_digest, block)
        assert digest not in self._nodes, "dedup-on-insert violated"
        node = PageNode(
            digest=digest,
            parent=None if parent is None else parent.digest,
            tokens=np.asarray(block, np.int64).copy(),
            start=start,
            page_id=page_id,
            ring=ring,
            logits=logits,
            last_hit=now,
        )
        self._nodes[digest] = node
        if parent is not None:
            parent.children += 1
        self.stats.published += 1
        return node

    def upgrade(self, node: PageNode, ring: Any = None, logits: Any = None) -> None:
        """Fill state an earlier publisher did not observe (a chunk
        schedule that skipped the boundary): the page content is already
        bit-identical by content addressing, so only the missing seam /
        logits payloads are added — never replaced."""
        if ring is not None and node.ring is None:
            node.ring = ring
        if logits is not None and node.logits is None:
            node.logits = logits

    def reclaimable_pages(self) -> int:
        """How many pages the leaf-first LRU eviction loop could free
        RIGHT NOW: the nodes of fully unreferenced subtrees (a refcount
        anywhere pins its whole ancestor chain — evicting an ancestor
        would orphan the referenced descendant). ``Engine.can_admit``
        counts these as available budget, mirroring what
        ``_reclaim_index_pages`` would actually evict."""
        pinned: set = set()
        for n in self._nodes.values():
            if n.refcount > 0:
                d: Optional[bytes] = n.digest
                while d is not None and d not in pinned:
                    pinned.add(d)
                    node = self._nodes.get(d)
                    d = node.parent if node is not None else None
        return len(self._nodes) - len(pinned)

    # ------------------------------------------------------------- evict
    def evictable(self) -> List[PageNode]:
        """Eviction candidates: refcount == 0 (shared pages are not
        victims) AND children == 0 (leaf-first — an interior eviction
        would orphan reachable descendants), LRU-first."""
        return sorted(
            (
                n for n in self._nodes.values()
                if n.refcount == 0 and n.children == 0
            ),
            key=lambda n: n.last_hit,
        )

    def evict_one(self) -> Optional[PageNode]:
        """Drop the LRU unreferenced leaf, returning its node (the engine
        discharges the page budget); None when nothing is evictable."""
        cands = self.evictable()
        if not cands:
            return None
        node = cands[0]
        del self._nodes[node.digest]
        if node.parent is not None and node.parent in self._nodes:
            self._nodes[node.parent].children -= 1
        self._free_pages.append(node.page_id)
        self.stats.evicted += 1
        return node

    # -------------------------------------------------------- invariants
    def verify_invariants(self) -> None:
        """Structural self-checks, composed into the engine's
        ``verify_invariants``: arena accounting (every node owns a
        distinct arena page; free + held == total), chain integrity
        (every non-root parent present — leaf-first eviction can never
        orphan), and child counts."""
        held = [n.page_id for n in self._nodes.values()]
        assert len(held) == len(set(held)), "node pages alias"
        assert len(held) + len(self._free_pages) == self.arena_total, (
            f"arena leak: {len(held)} held + {len(self._free_pages)} free "
            f"!= {self.arena_total}"
        )
        kids: Dict[bytes, int] = {}
        for n in self._nodes.values():
            assert n.refcount >= 0, "negative refcount"
            if n.parent is not None:
                assert n.parent in self._nodes, "orphaned chain node"
                kids[n.parent] = kids.get(n.parent, 0) + 1
        for n in self._nodes.values():
            assert n.children == kids.get(n.digest, 0), (
                "child count drift"
            )
