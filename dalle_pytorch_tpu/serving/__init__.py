"""Continuous-batching serving subsystem: request lifecycle, admission
control, page-pool pressure handling, and the replicated front door.
See engine.py for the single-replica architecture, router.py for the
fleet coordinator, and docs/DESIGN.md for the failure models."""

from .control import ControlConfig, Controller, Decision
from .engine import Engine, EngineConfig, check_accounting
from .journal import (
    JournalCorrupt,
    RequestJournal,
    replay_unfinished,
    request_from_record,
    request_to_record,
)
from .postdecode import PostDecodePipeline, StageConfig, StageSpec
from .router import ReplicaState, Router, RouterConfig
from .scheduler import PagePool, Scheduler, TokenBudget, pages_for
from .types import (
    Clock,
    EngineUnsupportedModel,
    FakeClock,
    Outcome,
    RejectReason,
    Request,
    RequestResult,
)

__all__ = [
    "Clock",
    "ControlConfig",
    "Controller",
    "Decision",
    "Engine",
    "EngineConfig",
    "EngineUnsupportedModel",
    "FakeClock",
    "JournalCorrupt",
    "Outcome",
    "PagePool",
    "PostDecodePipeline",
    "RejectReason",
    "ReplicaState",
    "Request",
    "RequestJournal",
    "RequestResult",
    "Router",
    "RouterConfig",
    "Scheduler",
    "StageConfig",
    "StageSpec",
    "TokenBudget",
    "check_accounting",
    "pages_for",
    "replay_unfinished",
    "request_from_record",
    "request_to_record",
]
