"""Continuous-batching serving subsystem: request lifecycle, admission
control, page-pool pressure handling. See engine.py for the architecture
and docs/DESIGN.md for the failure model."""

from .engine import Engine, EngineConfig, check_accounting
from .scheduler import PagePool, Scheduler, TokenBudget, pages_for
from .types import (
    Clock,
    EngineUnsupportedModel,
    FakeClock,
    Outcome,
    RejectReason,
    Request,
    RequestResult,
)

__all__ = [
    "Clock",
    "Engine",
    "EngineConfig",
    "EngineUnsupportedModel",
    "FakeClock",
    "Outcome",
    "PagePool",
    "RejectReason",
    "Request",
    "RequestResult",
    "Scheduler",
    "TokenBudget",
    "check_accounting",
    "pages_for",
]
