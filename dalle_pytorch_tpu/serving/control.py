"""Deterministic adaptive control loop over the engine's vitals.

The engine exposes knobs that are safe to move at runtime — but only
through channels that keep the trace contracts (DTL11x) holding by
construction, because every one of them is DATA to the serving jits,
never a static argument:

==================  ====================================================
knob                channel
==================  ====================================================
``spec_k``          the per-row VERIFY width is data (the ``length``
                    descriptor); the jit's static ``spec_k`` stays the
                    config ceiling it was traced with, so stepping the
                    effective width within [1, ceiling] can never
                    recompile — and exact-match acceptance keeps tokens
                    bit-identical at ANY width (engine._spec_iteration)
``token budget``    scheduler.TokenBudget is a frozen host-side policy
                    value; replacing it with a tighter/looser budget at
                    the SAME chunk width changes prefill grants, not
                    chunk shapes (the chunk width is what the trace
                    sees); the scheduler's head-of-line floor keeps
                    liveness at any budget
``watermark``       the degradation threshold engine._clamped_budget
                    compares occupancy against — pure host arithmetic
``prefix share``    a pages target applied through the index's own LRU
                    eviction tier (engine._reclaim_index_pages), which
                    only ever drops unreferenced cached pages
==================  ====================================================

The controller itself is a pure, deterministic function of its inputs:
same vitals window sequence -> same decision sequence (no wall clock, no
randomness), which is what makes the ``serve.control.decision`` event
journal a bit-deterministic replay log (docs/DESIGN.md §8.6). The
``control_stall`` fault site models a stuck/buggy controller: evaluation
raises, the ENGINE degrades every effective knob to its static default,
and the stall is typed and counted — decode progress never depends on
the control loop being alive.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.faults import FAULTS


@dataclass(frozen=True)
class ControlConfig:
    """Thresholds for the decision ladder. All comparisons are strict
    and hysteresis is explicit (a down-threshold and an up-threshold per
    knob), so the loop cannot oscillate on a flat signal."""

    # controller cadence, in worked engine iterations
    interval: int = 8
    # --- spec_k ladder: windowed accept rate vs the draft ceiling ---
    spec_accept_low: float = 0.45   # below: step the verify width down
    spec_accept_high: float = 0.85  # at/above: step back up toward ceiling
    # minimum drafted tokens in the window before adapting (noise gate)
    spec_min_drafts: int = 4
    # --- token-budget ladder: windowed max decode-iteration gap ---
    gap_high_s: float = 0.25        # above: tighten the budget one chunk
    gap_low_frac: float = 0.5       # below gap_high*frac: relax one chunk
    budget_min_frac: float = 0.5    # floor as a fraction of the default
    # --- watermark ladder: windowed deadline-miss rate ---
    miss_rate_high: float = 0.25    # above: clamp the effective watermark
    miss_rate_low_frac: float = 0.5 # below high*frac: restore the default
    watermark_clamp: float = 0.5    # the clamped effective watermark
    # --- prefix-arena ladder: windowed mean occupancy ---
    occupancy_shed: float = 0.9     # above: shed cached pages to the min
    occupancy_restore_frac: float = 0.5  # below shed*frac: stop shedding
    prefix_pages_min: int = 0       # pages target while shedding
    # decision log retention (oldest dropped past this)
    max_log: int = 4096


@dataclass(frozen=True)
class Decision:
    """One controller evaluation: the vitals it saw, the knobs it
    chose, and why — the audit record every ``serve.control.decision``
    event carries."""

    iteration: int
    vitals: Dict[str, float]
    knobs: Dict[str, Optional[float]]
    changed: bool
    stalled: bool = False
    reasons: Tuple[str, ...] = ()


class ControlStall(RuntimeError):
    """The controller evaluation failed (the ``control_stall`` fault, or
    a real bug in a ladder) — the engine catches this and degrades to
    static defaults."""


class Controller:
    """Deterministic vitals -> knobs mapper with explicit state.

    The constructor pins the static defaults (the knob values the engine
    was built with); ``evaluate`` walks the decision ladder and returns
    a ``Decision``; ``reset`` restores every knob to its default (the
    stall degrade). The engine owns APPLYING knobs — this class never
    touches the engine, so it is trivially testable and replayable.
    """

    def __init__(self, config: ControlConfig, *,
                 spec_k_ceiling: Optional[int] = None,
                 budget_default: Optional[int] = None,
                 chunk: int = 1,
                 watermark_default: float = 0.85,
                 prefix_enabled: bool = False):
        assert config.interval >= 1, config.interval
        self.config = config
        self.spec_k_ceiling = spec_k_ceiling
        self.budget_default = budget_default
        self.chunk = max(1, int(chunk))
        self.watermark_default = float(watermark_default)
        self.prefix_enabled = prefix_enabled
        self.log: List[Decision] = []
        self._knobs = self.defaults()

    def defaults(self) -> Dict[str, Optional[float]]:
        """The static-config knob values — the controller-off state and
        the stall-degrade target."""
        return {
            "spec_k": (
                float(self.spec_k_ceiling)
                if self.spec_k_ceiling is not None else None
            ),
            "budget": (
                float(self.budget_default)
                if self.budget_default is not None else None
            ),
            "watermark": self.watermark_default,
            # None = no target (the arena keeps its configured capacity)
            "prefix_pages_target": None,
        }

    @property
    def knobs(self) -> Dict[str, Optional[float]]:
        return dict(self._knobs)

    def reset(self) -> None:
        self._knobs = self.defaults()

    def record_stall(self, iteration: int,
                     vitals: Dict[str, float]) -> Decision:
        """Log the degrade-to-defaults decision after a stall (the
        engine calls this AFTER ``reset``)."""
        d = Decision(
            iteration=iteration, vitals=dict(vitals), knobs=self.knobs,
            changed=True, stalled=True, reasons=("control_stall",),
        )
        self._append(d)
        return d

    def evaluate(self, iteration: int,
                 vitals: Dict[str, float]) -> Decision:
        """Walk the decision ladder over one vitals snapshot. Raises
        ``ControlStall`` when the fault site is armed (the injectable
        stuck-controller drill)."""
        if FAULTS.take("control_stall"):
            raise ControlStall("control_stall fault armed")
        cfg = self.config
        k = dict(self._knobs)
        reasons: List[str] = []

        # 1) speculative verify width: track the windowed accept rate
        if k["spec_k"] is not None and (
            vitals.get("spec_drafted", 0.0) >= cfg.spec_min_drafts
        ):
            rate = vitals.get("spec_accept_rate", 0.0)
            cur = int(k["spec_k"])
            if rate < cfg.spec_accept_low and cur > 1:
                k["spec_k"] = float(cur - 1)
                reasons.append("spec_down")
            elif rate >= cfg.spec_accept_high and cur < self.spec_k_ceiling:
                k["spec_k"] = float(cur + 1)
                reasons.append("spec_up")

        # 2) token budget: bound prefill interference by the windowed
        # max decode-iteration gap
        if k["budget"] is not None:
            gap = vitals.get("decode_gap_s", 0.0)
            cur_b = int(k["budget"])
            floor = max(
                self.chunk,
                int(self.budget_default * cfg.budget_min_frac),
            )
            if gap > cfg.gap_high_s and cur_b > floor:
                k["budget"] = float(max(floor, cur_b - self.chunk))
                reasons.append("budget_down")
            elif (
                gap <= cfg.gap_high_s * cfg.gap_low_frac
                and cur_b < self.budget_default
            ):
                k["budget"] = float(
                    min(self.budget_default, cur_b + self.chunk)
                )
                reasons.append("budget_up")

        # 3) watermark: clamp admissions earlier while deadlines burn
        miss = vitals.get("deadline_miss_rate", 0.0)
        if miss > cfg.miss_rate_high:
            if k["watermark"] > cfg.watermark_clamp:
                k["watermark"] = cfg.watermark_clamp
                reasons.append("watermark_clamp")
        elif miss <= cfg.miss_rate_high * cfg.miss_rate_low_frac:
            if k["watermark"] != self.watermark_default:
                k["watermark"] = self.watermark_default
                reasons.append("watermark_restore")

        # 4) prefix-arena share: shed cached pages under sustained
        # occupancy pressure, stop shedding once it relaxes
        if self.prefix_enabled:
            occ = vitals.get("occupancy", 0.0)
            if occ > cfg.occupancy_shed:
                if k["prefix_pages_target"] != float(cfg.prefix_pages_min):
                    k["prefix_pages_target"] = float(cfg.prefix_pages_min)
                    reasons.append("prefix_shed")
            elif occ <= cfg.occupancy_shed * cfg.occupancy_restore_frac:
                if k["prefix_pages_target"] is not None:
                    k["prefix_pages_target"] = None
                    reasons.append("prefix_restore")

        changed = k != self._knobs
        self._knobs = k
        d = Decision(
            iteration=iteration, vitals=dict(vitals), knobs=dict(k),
            changed=changed, reasons=tuple(reasons),
        )
        self._append(d)
        return d

    def _append(self, d: Decision) -> None:
        self.log.append(d)
        if len(self.log) > self.config.max_log:
            del self.log[: len(self.log) - self.config.max_log]
