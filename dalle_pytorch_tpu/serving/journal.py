"""Durable request journal: an append-only JSONL write-ahead log of
admitted request descriptors, so a full-process crash replays unfinished
requests bit-identically on restart.

The serving fleet already survives *replica* death (serving/router.py:
in-flight work fails over to siblings and replays bit-identically by the
``(seed, position)`` sampling contract). What it did not survive is
*process* death: every queued and in-flight request simply vanished.
This module closes that gap with the same discipline the training side
uses for checkpoints (utils/resilience.py): every request the router
admits past its typed-reject gates is appended here as one JSON record
— request_id, prompt tokens, max_new_tokens, priority, seed, deadline:
exactly the fields that make replay bit-identical, because tokens depend
only on ``fold_in(key(seed), position)`` and never on wall-clock or
batch composition — and every terminal outcome is appended as a
completion record that makes replay IDEMPOTENT: on restart,
``unfinished()`` returns the admitted descriptors with no outcome
record, and resubmitting exactly those neither re-runs finished work
nor drops unfinished work.

Failure model (docs/DESIGN.md §8.3):

* **Torn tail** — a crash mid-append leaves a final record that is
  truncated (no trailing newline, or unparseable JSON). That is the
  ONLY corruption an append-only log can legally contain, so the loader
  detects it, DROPS it, and counts it (``serve.journal.torn``; the
  ``journal_torn`` fault site truncates the tail in-memory so the path
  is drillable on CPU). The dropped request was never acknowledged
  durable — the client-retry contract, same as a request shed at the
  door.
* **Mid-file corruption** — an unparseable record *before* the tail
  cannot come from a crash (appends are sequential); it is bit rot, and
  the loader raises the typed ``JournalCorrupt`` rather than guessing
  (``tools/verify_ckpt.py --serving`` maps it to exit 2).
* **Graceful shutdown** — ``seal()`` flushes and writes the sidecar
  file manifest (``utils/resilience.py:write_file_manifest``), the
  single-file analog of the checkpoint two-phase commit; ``verify()``
  checks it. A crash leaves no manifest — the loader still recovers via
  the torn-tail scan; the manifest's job is to let an operator (or the
  SIGTERM drain path) distinguish "cleanly sealed" from "recovered".

Pure host-side, no jax import — unit-testable like the scheduler.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.faults import FAULTS
from ..utils.metrics import counters
from ..utils.resilience import (
    FILE_MANIFEST_SUFFIX,
    verify_file_manifest,
    write_file_manifest,
)
from .types import Request

_ADMITTED = "admitted"
_OUTCOME = "outcome"
# Stage-boundary records (docs/DESIGN.md §8.5): one per COMPLETED
# post-decode stage boundary — ``stage="tokens"`` carries the finished
# image tokens, ``stage="vae_decode"`` the decoded image — so a crash
# mid-VAE or mid-rerank replays from the last completed stage instead of
# re-running it. Duplicates are legal (failover re-announces); the
# loader keeps the LAST record per (request, stage).
_STAGE = "stage"
_KINDS = (_ADMITTED, _OUTCOME, _STAGE)


def image_to_payload(image: np.ndarray) -> dict:
    """JSON-able encoding of a decoded image: raw bytes (base64) plus
    shape/dtype and a content digest so bit rot is detected on load,
    not silently decoded into a wrong image."""
    arr = np.ascontiguousarray(image)
    raw = arr.tobytes()
    return {
        "b64": base64.b64encode(raw).decode("ascii"),
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "sha256": hashlib.sha256(raw).hexdigest(),
    }


def image_from_payload(payload: dict) -> np.ndarray:
    """Inverse of ``image_to_payload``; raises ``JournalCorrupt`` on a
    digest mismatch (a stage record that decodes wrong is bit rot — the
    mid-file corruption class, never a torn tail)."""
    raw = base64.b64decode(payload["b64"])
    if hashlib.sha256(raw).hexdigest() != payload["sha256"]:
        raise JournalCorrupt("stage image payload digest mismatch")
    return np.frombuffer(raw, dtype=np.dtype(payload["dtype"])).reshape(
        payload["shape"]
    ).copy()


class JournalCorrupt(RuntimeError):
    """A non-tail journal record failed to parse — bit rot, not a torn
    append. Loaders must not guess past it."""


def request_to_record(request: Request, now: float) -> dict:
    """The JSON-able restorable descriptor of one request: every field
    replay needs to be bit-identical, nothing else. The deadline is
    stored BOTH absolute (same-clock restarts, debugging) and as the
    REMAINING budget at admission — an absolute instant on one
    process's monotonic clock is meaningless on the next process's, so
    replay rebases the remaining budget onto the new clock
    (``request_from_record(now=...)``)."""
    return {
        "kind": _ADMITTED,
        "request_id": request.request_id,
        "prompt": [int(t) for t in np.asarray(request.prompt).reshape(-1)],
        "max_new_tokens": int(request.max_new_tokens),
        "deadline": (
            None if request.deadline is None else float(request.deadline)
        ),
        "deadline_remaining": (
            None if request.deadline is None
            else max(0.0, float(request.deadline) - float(now))
        ),
        "priority": int(request.priority),
        "seed": int(request.seed),
        "t": float(now),
    }


def request_from_record(rec: dict, now: Optional[float] = None) -> Request:
    """Rebuild a journaled request. With ``now`` (the RESTARTED
    process's clock), a journaled deadline is rebased: the remaining
    budget recorded at admission starts over from ``now`` — the old
    absolute instant lives on another incarnation's clock epoch.
    Without ``now`` the absolute value is used verbatim (same-process
    restart, tests)."""
    deadline = rec.get("deadline")
    if now is not None and deadline is not None:
        remaining = rec.get("deadline_remaining")
        deadline = None if remaining is None else float(now) + remaining
    return Request(
        request_id=rec["request_id"],
        prompt=np.asarray(rec["prompt"], np.int32),
        max_new_tokens=int(rec["max_new_tokens"]),
        deadline=deadline,
        priority=int(rec.get("priority", 0)),
        seed=int(rec.get("seed", 0)),
    )


class RequestJournal:
    """See module docstring. One file, one writer (the router holds its
    lock around every append), any number of post-crash readers."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = str(path)
        self._fsync = fsync
        self._fh = None

    # ------------------------------------------------------------ writes

    def _append(self, rec: dict) -> None:
        if self._fh is None:
            p = Path(self.path)
            p.parent.mkdir(parents=True, exist_ok=True)
            # reopening a sealed journal makes its manifest stale — drop
            # it so the journal reads as live/unsealed again (seal()
            # rewrites it at the next graceful shutdown)
            stale = Path(self.path + FILE_MANIFEST_SUFFIX)
            if stale.exists():
                stale.unlink()
            self._fh = open(self.path, "a", encoding="utf-8")
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        # flush every record: the WAL's whole point is surviving the
        # process; fsync (surviving the HOST) is opt-in because it turns
        # every admission into a disk round trip
        self._fh.flush()
        if self._fsync:
            os.fsync(self._fh.fileno())

    def append_admitted(self, request: Request, now: float) -> None:
        """Record one admission — called AFTER every typed-reject gate
        passed, so the journal holds exactly the requests the fleet owes
        a terminal outcome."""
        self._append(request_to_record(request, now))
        counters.inc("serve.journal.appended")

    def append_outcome(self, request_id: str, outcome: str,
                       now: float) -> None:
        """Record one terminal outcome — what makes replay idempotent."""
        self._append({
            "kind": _OUTCOME, "request_id": request_id,
            "outcome": outcome, "t": float(now),
        })

    def append_stage(self, request_id: str, stage: str, payload: dict,
                     now: float) -> None:
        """Record one completed post-decode stage boundary. ``payload``
        may carry raw arrays — ``{"tokens": ids}`` or
        ``{"image": ndarray}`` — which are encoded durably here
        (``image_to_payload``), so the pipeline's ``on_stage`` hook can
        hand over its in-memory values verbatim."""
        enc: dict = {}
        for k, v in payload.items():
            if k == "image":
                enc[k] = image_to_payload(np.asarray(v, np.float32))
            elif isinstance(v, np.ndarray):
                enc[k] = [int(t) for t in v.reshape(-1)]
            else:
                enc[k] = v
        self._append({
            "kind": _STAGE, "request_id": request_id, "stage": stage,
            "payload": enc, "t": float(now),
        })
        counters.inc("serve.stage.journal_records")

    def seal(self) -> None:
        """Graceful-shutdown flush: close the handle and write the
        sidecar manifest (two-phase: the artifact is complete before the
        manifest names it). Safe to call with nothing ever appended."""
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            self._fh = None
        if Path(self.path).exists():
            write_file_manifest(self.path)

    def close(self) -> None:
        """Drop the handle WITHOUT sealing — the crash-simulation seam
        (tests/chaos): the file is exactly what a dead process left."""
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    # ------------------------------------------------------------- reads

    @classmethod
    def load(cls, path: str, count: bool = True) -> Tuple[List[dict], int]:
        """Parse the journal -> (records, torn_tail_count).

        The ``journal_torn`` fault truncates the tail record in-memory
        (the crash-mid-append shape) before parsing. A trailing segment
        that fails to parse — or lacks its newline — is the torn tail:
        dropped and, when ``count`` is set, counted
        (``serve.journal.torn``). An unparseable record anywhere
        EARLIER is ``JournalCorrupt``. ``count=False`` is for
        SECONDARY reads (verification, outcome reconciliation): one
        real torn tail must move the counter — and consume the armed
        drill — exactly once per recovery, at the replay read, no
        matter how many times the file is re-parsed."""
        p = Path(path)
        if not p.exists():
            return [], 0
        data = p.read_text(encoding="utf-8")
        if data and count and FAULTS.take("journal_torn"):
            counters.inc("serve.fault_journal_torn")
            # tear mid-record: drop the trailing newline plus a few bytes
            data = data[: max(0, len(data) - 5)]
        segments = data.split("\n")
        complete, tail = segments[:-1], segments[-1]
        records: List[dict] = []
        torn = 0
        for i, line in enumerate(complete):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or rec.get("kind") not in _KINDS:
                    raise ValueError(f"not a known journal record: {line[:60]!r}")
            except ValueError as e:
                if i == len(complete) - 1 and not tail:
                    torn += 1  # last complete-looking line, torn content
                    break
                raise JournalCorrupt(
                    f"{path}: unparseable non-tail record at line "
                    f"{i + 1}: {e}"
                ) from e
            records.append(rec)
        if tail.strip():
            # bytes past the last newline: a torn append by definition
            torn += 1
        if torn and count:
            counters.inc("serve.journal.torn", torn)
        return records, torn

    @classmethod
    def unfinished(cls, path: str, now: Optional[float] = None,
                   count: bool = True) -> List[Request]:
        """The replay set: admitted descriptors with no outcome record,
        in admission order (re-admitted duplicates collapse onto the
        first record — replay resubmission re-appends them). ``now``
        rebases journaled deadlines onto the restarted process's clock
        (see ``request_from_record``). This is THE recovery read, so it
        counts torn tails by default; pass ``count=False`` from
        inspection tools."""
        records, _ = cls.load(path, count=count)
        admitted: Dict[str, dict] = {}
        done: set = set()
        for rec in records:
            if rec["kind"] == _ADMITTED:
                admitted.setdefault(rec["request_id"], rec)
            elif rec["kind"] == _OUTCOME:
                done.add(rec["request_id"])
            # _STAGE records mark progress, not completion
        return [
            request_from_record(rec, now=now)
            for rid, rec in admitted.items()
            if rid not in done
        ]

    @classmethod
    def stages(cls, path: str) -> Dict[str, Dict[str, dict]]:
        """request_id -> {stage -> payload} for every journaled stage
        boundary (last record per (request, stage) wins — failover
        re-announcements are idempotent). A secondary read: never counts
        torn tails."""
        records, _ = cls.load(path, count=False)
        out: Dict[str, Dict[str, dict]] = {}
        for rec in records:
            if rec["kind"] == _STAGE:
                out.setdefault(rec["request_id"], {})[rec["stage"]] = (
                    rec["payload"]
                )
        return out

    @classmethod
    def outcomes(cls, path: str) -> Dict[str, str]:
        """request_id -> outcome for every journaled terminal record.
        A secondary read: never counts torn tails (the replay read
        does)."""
        records, _ = cls.load(path, count=False)
        return {
            rec["request_id"]: rec["outcome"]
            for rec in records if rec["kind"] == _OUTCOME
        }

    @classmethod
    def verify(cls, path: str) -> Tuple[bool, str]:
        """Operator verification (tools/verify_ckpt.py --serving):
        sidecar manifest (sealed journals) plus a full parse scan. A
        recovered-but-unsealed journal verifies iff the scan is clean
        ("no manifest" is reported but not fatal — a crash legally
        leaves no manifest)."""
        ok, reason = verify_file_manifest(path)
        if not ok and reason != "no manifest":
            return False, reason
        try:
            _, torn = cls.load(path, count=False)
        except JournalCorrupt as e:
            return False, str(e)
        if torn:
            return True, f"ok ({torn} torn tail record dropped)"
        if not ok:
            return True, "ok (unsealed: no manifest — crash recovery)"
        return True, "ok"


def replay_unfinished(path: str, submit: Callable[[Request], object],
                      reconcile: Optional[Callable[[str, str], None]] = None,
                      now: Optional[float] = None,
                      submit_staged: Optional[Callable] = None) -> List[str]:
    """Resubmit every unfinished journaled request through ``submit``
    (typically ``Router.submit`` on the restarted process), counting
    each under ``serve.journal.replayed``; returns the ids that were
    genuinely re-admitted. A resubmission ``submit`` rejects TYPED
    (non-None return — e.g. queue_full during a large replay burst) is
    NOT counted replayed: its typed result is already in the router's
    results (and journaled as the outcome), so the caller sees the
    reject rather than a silent drop. ``now`` rebases journaled
    deadlines onto the restarted clock; ``reconcile(request_id,
    outcome)`` — optional — receives every ALREADY-finished journaled
    outcome so a restart harness can hand clients their pre-crash
    results without re-running them (the idempotency half of the
    contract).

    ``submit_staged(request, tokens, image=None)`` — optional, typically
    ``Router.submit_staged`` — receives every unfinished request whose
    journal carries stage-boundary records (DESIGN.md §8.5): the request
    resumes from the LAST completed post-decode stage (tokens done →
    VAE_DECODE; image decoded → CLIP_RERANK) instead of re-running token
    decode, which is what makes a crash mid-VAE or mid-rerank replay
    idempotent AND cheap. Without ``submit_staged`` (or without stage
    records) the request replays from the top — still bit-identical by
    the sampling contract, just re-doing the work."""
    if reconcile is not None:
        for rid, outcome in RequestJournal.outcomes(path).items():
            reconcile(rid, outcome)
    staged = RequestJournal.stages(path) if submit_staged is not None else {}
    replayed: List[str] = []
    for request in RequestJournal.unfinished(path, now=now):
        st = staged.get(request.request_id)
        if st is not None and "tokens" in st:
            tokens = np.asarray(st["tokens"]["tokens"], np.int32)
            img_payload = st.get("vae_decode")
            image = (None if img_payload is None
                     else image_from_payload(img_payload["image"]))
            res = submit_staged(request, tokens, image=image)
        else:
            res = submit(request)
        if res is not None:
            continue  # typed reject: delivered via results, not replayed
        counters.inc("serve.journal.replayed")
        replayed.append(request.request_id)
    return replayed
